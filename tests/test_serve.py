"""``advspec serve`` — admission control, fair share, tiers, brownout,
preemption, quotas, drain, and the daemon transport (ISSUE 14).

Layered like the subsystem: protocol schema first, then the scheduler
state machine driven synchronously (deterministic, no sockets), then
the gate + pump + reentrant round driver with real threads, then the
asyncio daemon over a real unix socket (the tier-1 mock-engine smoke,
``chaos``-marked), then the tooling (obs_dump rendering, bench_trend
schema, the GL-LIFECYCLE live-fire pin).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

import pytest

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.debate.journal import RoundJournal
from adversarial_spec_tpu.engine import spec as spec_mod
from adversarial_spec_tpu.engine.mock import MockEngine
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.obs.events import SERVE_OPS, SERVE_TIERS, validate_event
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.serve import gate, protocol
from adversarial_spec_tpu.serve.client import ServeClient
from adversarial_spec_tpu.serve.daemon import ServeDaemon
from adversarial_spec_tpu.serve.driver import run_debate
from adversarial_spec_tpu.serve.gate import EnginePump, Submission
from adversarial_spec_tpu.serve.sched import (
    ServeScheduler,
    Unit,
    estimate_tokens,
)

SPEC = (
    "## Goals\nServe heavy traffic from millions of users, fast.\n"
    "## Constraints\n" + "The daemon SHALL shed, not collapse. " * 12
)


def _unit(
    tenant="t0",
    tier="interactive",
    debate="d1",
    index=0,
    engine=None,
    model="mock://critic",
    max_new=128,
    consumer=None,
    on_stream=None,
    submission=None,
):
    req = ChatRequest(
        model=model, system="sys", user=f"Debate round 1\n{SPEC}"
    )
    return Unit(
        debate=debate,
        tenant=tenant,
        tier=tier,
        index=index,
        request=req,
        params=SamplingParams(max_new_tokens=max_new, greedy=True),
        engine=engine,
        consumer=consumer,
        on_stream=on_stream,
        submission=submission,
    )


def _completion(tokens_in=100, tokens_out=50, cached=0):
    from adversarial_spec_tpu.debate.usage import Usage

    return Completion(
        text="x" * (tokens_out * 4),
        usage=Usage(
            input_tokens=tokens_in,
            output_tokens=tokens_out,
            cached_tokens=cached,
        ),
    )


class TestProtocol:
    def test_self_check_clean(self):
        assert protocol.self_check() == []

    def test_tiers_match_obs_vocabulary(self):
        # One drift axis less: the wire tier names ARE the event tier
        # names obs_dump validates against.
        assert tuple(protocol.TIERS) == tuple(SERVE_TIERS)

    def test_shed_reasons_are_closed_vocabulary(self):
        for reason in protocol.SHED_REASONS:
            ev = protocol.shed_event("r1", reason, 1.5, "why")
            assert ev["event"] == "shed" and ev["retry_after_s"] == 1.5

    def test_validate_request_fires(self):
        good = {
            "op": "debate",
            "id": "c1",
            "tenant": "t0",
            "spec": SPEC,
            "models": ["mock://agree"],
        }
        assert protocol.validate_request(good) == []
        assert protocol.validate_request({**good, "op": "zap"})
        assert protocol.validate_request({**good, "tier": "bulk"})
        assert protocol.validate_request({**good, "models": []})
        assert protocol.validate_request({**good, "round": "one"})
        assert protocol.validate_request({**good, "mystery": 1})
        missing_id = {k: v for k, v in good.items() if k != "id"}
        assert protocol.validate_request(missing_id)

    def test_decode_tolerates_garbage(self):
        assert protocol.decode(b"not json\n") is None
        assert protocol.decode(b"[1,2]\n") is None
        assert protocol.decode(b"") is None
        assert protocol.decode(protocol.encode({"op": "ping", "id": "x"}))


class TestServeEventSchema:
    def test_good_event_validates(self):
        ev = obs_mod.ServeEvent(op="shed", tenant="t0", tier="batch",
                                debate="d1", reason="backlog", tokens=10)
        from adversarial_spec_tpu.obs.events import event_to_dict

        assert validate_event(event_to_dict(1, ev)) == []

    def test_unknown_op_and_tier_fire(self):
        from adversarial_spec_tpu.obs.events import event_to_dict

        good = event_to_dict(1, obs_mod.ServeEvent())
        assert validate_event({**good, "op": "vanish"})
        assert validate_event({**good, "tier": "bulk"})

    def test_op_vocabulary_covers_lifecycle(self):
        # The daemon request lifecycle (docs/serving.md) is exactly the
        # event vocabulary: every exit the GL-LIFECYCLE machine guards
        # has an op, plus the brownout transitions.
        for op in (
            "accepted", "queued", "running", "finished", "shed",
            "preempted", "drained", "brownout_enter", "brownout_exit",
        ):
            assert op in SERVE_OPS


class TestAdmission:
    def test_queue_depth_cap_typed_shed(self):
        serve_mod.configure(max_queue_depth=2, max_backlog_tokens=10**9)
        sched = ServeScheduler()
        assert sched.try_admit("t0", "interactive", "d1", 100) is None
        assert sched.try_admit("t0", "interactive", "d2", 100) is None
        shed = sched.try_admit("t0", "interactive", "d3", 100)
        assert shed is not None and shed.reason == "queue_full"
        assert shed.retry_after_s >= 0.0
        # Another tenant is unaffected: the cap is per tenant.
        assert sched.try_admit("t1", "interactive", "d4", 100) is None
        # Completion frees the slot.
        sched.finish_debate("d1")
        assert sched.try_admit("t0", "interactive", "d5", 100) is None

    def test_backlog_cap_typed_shed_with_retry_after(self):
        serve_mod.configure(max_queue_depth=100, max_backlog_tokens=1000)
        sched = ServeScheduler()
        assert sched.try_admit("t0", "interactive", "d1", 700) is None
        shed = sched.try_admit("t1", "interactive", "d2", 700)
        assert shed is not None and shed.reason == "backlog"
        assert shed.retry_after_s > 0.0

    def test_draining_shed(self):
        sched = ServeScheduler()
        sched.begin_drain()
        shed = sched.try_admit("t0", "interactive", "d1", 10)
        assert shed is not None and shed.reason == "draining"

    def test_accounting_ledger(self):
        serve_mod.configure(max_queue_depth=2, max_backlog_tokens=1000)
        sched = ServeScheduler()
        sched.try_admit("t0", "interactive", "d1", 700)
        assert sched.try_admit("t1", "interactive", "d2", 700).reason == (
            "backlog"
        )
        snap = serve_mod.snapshot()
        assert snap["accepted_debates"] == 1
        assert snap["shed_debates"] == 1
        assert snap["shed_fraction"] == 0.5


class TestFairShare:
    def _drain_order(self, sched, n):
        """Pop n units one at a time, charging each before the next
        pick — the stride scheduler's feedback loop, synchronously."""
        order = []
        for _ in range(n):
            batch = sched.next_batch(timeout=0.01)
            assert len(batch) == 1
            u = batch[0]
            order.append(u.tenant)
            # Heavy tenant pays 10x per completion.
            cost = 1000 if u.tenant == "heavy" else 100
            sched.on_dispatch_complete([u], [_completion(cost, 0)])
        return order

    def test_stride_interleave_by_token_cost(self):
        serve_mod.configure(max_dispatch_batch=1)
        sched = ServeScheduler()
        sched.try_admit("heavy", "interactive", "dh", 10000)
        sched.try_admit("light", "interactive", "dl", 10000)
        sched.submit_units(
            [_unit(tenant="heavy", debate="dh", index=i) for i in range(3)]
        )
        sched.submit_units(
            [_unit(tenant="light", debate="dl", index=i) for i in range(8)]
        )
        order = self._drain_order(sched, 11)
        # After one heavy completion (1000 tokens) the light tenant
        # (100/completion) must be served MANY times before heavy runs
        # again: passes advance by actual tokens paid.
        first_heavy = order.index("heavy")
        second_heavy = order.index("heavy", first_heavy + 1)
        assert second_heavy - first_heavy >= 5, order

    def test_interactive_strictly_before_batch(self):
        serve_mod.configure(max_dispatch_batch=1)
        sched = ServeScheduler()
        sched.submit_units([_unit(tier="batch", debate="db", index=0)])
        sched.submit_units([_unit(tier="interactive", debate="di", index=0)])
        batch = sched.next_batch(timeout=0.01)
        assert batch[0].tier == "interactive"

    def test_same_model_units_coalesce_into_one_dispatch(self):
        serve_mod.configure(max_dispatch_batch=4)
        eng = object()
        sched = ServeScheduler()
        sched.submit_units(
            [_unit(debate="d1", index=i, engine=eng) for i in range(3)]
        )
        batch = sched.next_batch(timeout=0.01)
        assert len(batch) == 3  # N rows of one batched decode
        sched.on_dispatch_complete(batch, [_completion()] * 3)

    def test_queue_wait_and_events_emitted(self):
        sched = ServeScheduler()
        u = _unit()
        sched.submit_units([u])
        batch = sched.next_batch(timeout=0.01)
        sched.on_dispatch_complete(batch, [_completion()])
        types = [
            e["op"]
            for e in obs_mod.recorder.events()
            if e["type"] == "serve"
        ]
        assert types[-3:] == ["queued", "running", "finished"]
        for e in obs_mod.recorder.events():
            assert validate_event(e) == [], e


class TestBrownout:
    def test_enter_lowers_gamma_exit_restores(self):
        serve_mod.configure(
            max_queue_depth=100,
            max_backlog_tokens=1000,
            brownout_gamma=2,
        )
        prev_gamma = spec_mod.config().gamma
        try:
            sched = ServeScheduler()
            assert sched.try_admit("t0", "interactive", "d1", 800) is None
            assert sched.brownout  # 800 >= 0.75 * 1000
            assert spec_mod.config().gamma == 2
            # Batch admissions pause, typed; interactive still fits.
            shed = sched.try_admit("t0", "batch", "d2", 10)
            assert shed is not None and shed.reason == "brownout"
            assert sched.try_admit("t1", "interactive", "d3", 100) is None
            # Draining the backlog below the exit fraction restores γ.
            sched.finish_debate("d1")
            assert not sched.brownout
            assert spec_mod.config().gamma == prev_gamma
            snap = serve_mod.snapshot()
            assert snap["brownout_entries"] == 1
            assert snap["brownout_exits"] == 1
        finally:
            spec_mod.configure(gamma=prev_gamma)

    def test_brownout_events_in_recorder(self):
        serve_mod.configure(max_queue_depth=100, max_backlog_tokens=1000)
        prev_gamma = spec_mod.config().gamma
        try:
            sched = ServeScheduler()
            sched.try_admit("t0", "interactive", "d1", 900)
            sched.finish_debate("d1")
        finally:
            spec_mod.configure(gamma=prev_gamma)
        ops = [
            e["op"]
            for e in obs_mod.recorder.events()
            if e["type"] == "serve"
        ]
        assert "brownout_enter" in ops and "brownout_exit" in ops


class TestQuota:
    """ISSUE 14 satellite: quota accounting edge cases."""

    def test_admission_shed_when_exhausted(self):
        serve_mod.configure(tenant_quota_tokens=100)
        sched = ServeScheduler()
        assert sched.try_admit("t0", "interactive", "d1", 10) is None
        u = _unit(debate="d1")
        sched.submit_units([u])
        batch = sched.next_batch(timeout=0.01)
        sched.on_dispatch_complete(batch, [_completion(200, 100)])
        shed = sched.try_admit("t0", "interactive", "d2", 10)
        assert shed is not None and shed.reason == "quota"
        # Another tenant's quota is its own.
        assert sched.try_admit("t1", "interactive", "d3", 10) is None

    def test_quota_exhausted_mid_round_sheds_remaining_units(self):
        """Quota dies between opponent 1 and opponents 2-3: the
        remaining units shed with a TYPED error completion (no retry
        ladder — transient=False) and the round still resolves."""
        serve_mod.configure(tenant_quota_tokens=250, max_dispatch_batch=1)
        sched = ServeScheduler()
        sched.try_admit("t0", "interactive", "d1", 10)
        units = [_unit(debate="d1", index=i) for i in range(3)]
        sched.submit_units(units)
        first = sched.next_batch(timeout=0.01)
        sched.on_dispatch_complete(first, [_completion(200, 100)])  # 300 paid
        # Quota now negative: the next two picks shed at dispatch.
        assert sched.next_batch(timeout=0.01) == []
        for u in units[1:]:
            assert u.done.is_set()
            assert not u.completion.ok
            assert u.completion.error.startswith("shed (quota)")
            assert u.completion.transient is False
            assert u.state == "shed"
        assert units[0].completion.ok
        snap = serve_mod.snapshot()
        assert snap["units_shed"] == 2

    def test_refill_race_with_queued_admission(self):
        """A unit queued while quota is exhausted dispatches the moment
        a refill lands — the refill is not lost to the queue."""
        serve_mod.configure(tenant_quota_tokens=100, max_dispatch_batch=1)
        sched = ServeScheduler()
        sched.try_admit("t0", "interactive", "d1", 10)
        u1, u2 = _unit(debate="d1", index=0), _unit(debate="d1", index=1)
        sched.submit_units([u1])
        sched.on_dispatch_complete(
            sched.next_batch(timeout=0.01), [_completion(200, 100)]
        )
        sched.submit_units([u2])  # queued with quota exhausted
        assert sched.refill_quota("t0", 1000) > 0
        batch = sched.next_batch(timeout=0.01)
        assert batch == [u2]  # dispatched, not shed
        sched.on_dispatch_complete(batch, [_completion()])
        assert u2.completion.ok

    def test_quota_error_classifies_as_shed_not_model_fault(self):
        from adversarial_spec_tpu.resilience.faults import (
            FaultKind,
            classify_message,
        )

        assert (
            classify_message("shed (quota): tenant 't0' token quota "
                             "exhausted")
            is FaultKind.SHED
        )
        assert (
            classify_message("drained: daemon shutting down")
            is FaultKind.SHED
        )
        assert FaultKind.SHED.transient is False

    def test_shed_does_not_trip_breaker(self):
        """A policy shed must not open the model's circuit: a drain
        storm counting as failures would ban every opponent (found by
        the SIGTERM drain drill)."""
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round

        breakers = breaker_mod.BreakerRegistry(threshold=1)

        class ShedEngine:
            def validate(self, model):
                return None

            def chat(self, requests, params):
                return [
                    Completion(
                        error="shed (quota): tenant quota exhausted",
                        transient=False,
                    )
                    for _ in requests
                ]

        from adversarial_spec_tpu.engine import dispatch

        eng = ShedEngine()
        old = dict(dispatch._ENGINE_CACHE)
        dispatch._ENGINE_CACHE["mock"] = eng
        try:
            result = run_round(
                SPEC,
                ["mock://critic"],
                cfg=RoundConfig(breakers=breakers),
            )
        finally:
            dispatch._ENGINE_CACHE.clear()
            dispatch._ENGINE_CACHE.update(old)
        assert not result.responses[0].ok
        assert breakers.breaker("mock://critic").state == "closed"


class TestPreemption:
    def _pump_once(self, sched, engine):
        batch = sched.next_batch(timeout=0.05)
        assert batch
        EnginePump(sched)._execute(batch)
        return batch

    def test_batch_preempted_then_readmitted_byte_prefix_parity(self):
        """ISSUE 14 satellite: a batch unit preempted for interactive
        pressure re-queues and its eventual transcript carries the
        preempted partial as a byte prefix (mock determinism + the
        batcher's byte-parity guarantee)."""
        serve_mod.configure(max_dispatch_batch=1, preempt_grace_s=0.0)
        eng = MockEngine()
        sched = ServeScheduler()
        gate.install(sched)
        try:
            batch_unit = _unit(
                tier="batch", debate="db", model="mock://critic", engine=eng
            )
            sched.submit_units([batch_unit])
            picked = sched.next_batch(timeout=0.05)
            assert picked == [batch_unit]
            # Interactive work arrives while the batch unit holds the
            # engine: the composed consumer must cancel it mid-stream.
            inter = _unit(
                tier="interactive", debate="di", model="mock://agree",
                engine=eng,
            )
            sched.submit_units([inter])
            EnginePump(sched)._execute(picked)
            assert batch_unit.state == "queued"  # released + readmitted
            assert not batch_unit.done.is_set()
            assert batch_unit.preempt_partials
            snap = serve_mod.snapshot()
            assert snap["units_preempted"] == 1
            assert snap["units_readmitted"] == 1
            # Interactive unit dispatches next (strict priority).
            nxt = sched.next_batch(timeout=0.05)
            assert nxt == [inter]
            EnginePump(sched)._execute(nxt)
            assert inter.completion.ok
            # The batch unit re-runs to completion; byte-prefix parity.
            again = sched.next_batch(timeout=0.05)
            assert again == [batch_unit]
            EnginePump(sched)._execute(again)
            assert batch_unit.completion.ok
            assert batch_unit.completion.text.startswith(
                batch_unit.preempt_partials[0]
            )
            assert len(batch_unit.completion.text) > len(
                batch_unit.preempt_partials[0]
            )
        finally:
            gate.uninstall()

    def test_interactive_never_preempted(self):
        serve_mod.configure(preempt_grace_s=0.0)
        sched = ServeScheduler()
        u = _unit(tier="interactive")
        assert sched.should_preempt(u) is False

    def test_grace_respects_clock(self):
        serve_mod.configure(preempt_grace_s=100.0)
        now = [0.0]
        sched = ServeScheduler(clock=lambda: now[0])
        sched.submit_units([_unit(tier="interactive", debate="di")])
        batch_unit = _unit(tier="batch", debate="db")
        assert sched.should_preempt(batch_unit) is False  # within grace
        now[0] = 200.0
        assert sched.should_preempt(batch_unit) is True

    def test_caller_cancel_beats_preemption(self):
        """An early-convergence cancel must resolve as FINISHED (clean
        truncation), never as a preemption re-queue, even when the
        preempt flag is also up."""
        serve_mod.configure(max_dispatch_batch=1)
        eng = MockEngine()
        sched = ServeScheduler()
        unit = _unit(
            tier="batch",
            model="mock://agree?agree_tail=8",
            engine=eng,
            # Caller cancels at the FIRST delivery — the same delivery
            # at which the raised preempt flag would otherwise fire.
            consumer=lambda i, text: False,
        )
        unit.preempt_requested = True
        sched.submit_units([unit])
        batch = sched.next_batch(timeout=0.05)
        EnginePump(sched)._execute(batch)
        assert unit.done.is_set()
        assert unit.state == "finished"
        assert unit.completion.cancelled


class TestDrain:
    def test_force_drain_sheds_queued_units_typed(self):
        sched = ServeScheduler()
        units = [_unit(debate="d1", index=i) for i in range(3)]
        sched.submit_units(units)
        n = sched.force_drain()
        assert n == 3
        for u in units:
            assert u.done.is_set()
            assert u.completion.error.startswith("drained:")
            assert u.completion.transient is False
            assert u.state == "drained"
        assert serve_mod.snapshot()["units_drained"] == 3

    def test_drain_mid_round_journal_resumable(self, tmp_path):
        """The drain contract end to end, deterministically: a 4-
        opponent journaled round gets exactly 2 opponents served (the
        pump is driven by hand), the drain forces the rest, and a
        resumed round replays the 2 durable completions with zero
        engine work."""
        serve_mod.configure(max_dispatch_batch=1)
        eng = MockEngine()
        sched = ServeScheduler()
        gate.install(sched)
        models = [f"mock://critic?v={k}" for k in range(4)]
        journal = RoundJournal("serve-drain", journal_dir=tmp_path)
        result_box = {}

        def debate_thread():
            from adversarial_spec_tpu.debate.core import RoundConfig, run_round

            with gate.submission(Submission(tenant="t0", debate="d1")):
                result_box["result"] = run_round(
                    SPEC,
                    models,
                    cfg=RoundConfig(
                        journal=journal, trace_scope="serve-drain"
                    ),
                )

        th = threading.Thread(target=debate_thread, daemon=True)
        try:
            th.start()
            # Serve exactly two opponents, then force the drain.
            for _ in range(2):
                batch = sched.next_batch(timeout=2.0)
                assert batch, "round driver never submitted units"
                EnginePump(sched)._execute(batch)
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                if sched.force_drain() or sched.idle():
                    break
                time.sleep(0.01)
            th.join(timeout=5.0)
            assert not th.is_alive()
        finally:
            gate.uninstall()
        result = result_box["result"]
        ok = [r for r in result.responses if r.ok]
        failed = [r for r in result.responses if not r.ok]
        assert len(ok) == 2 and len(failed) == 2
        for r in failed:
            assert "drained" in r.error
        # The journal holds exactly the two durable completions; a
        # resumed round serves them byte-identically with zero engine
        # work for those opponents.
        replay = journal.replay(1, SPEC, models)
        assert sorted(replay) == [i for i, r in enumerate(result.responses) if r.ok]
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round

        resumed = run_round(
            SPEC, models, cfg=RoundConfig(journal=journal)
        )
        assert all(r.ok for r in resumed.responses)
        assert int(resumed.tracer.counters.get("journal.served", 0)) == 2
        for i in replay:
            assert (
                resumed.responses[i].critique
                == result.responses[i].critique
            )


class TestShutdownSafety:
    """Review-found regression pins: a debate thread that reaches the
    scheduler AFTER shutdown/force-drain must resolve immediately, not
    block forever on a queue nobody serves."""

    def test_submit_after_stop_resolves_drained(self):
        sched = ServeScheduler()
        sched.stop()
        units = [_unit(debate="late", index=i) for i in range(2)]
        sched.submit_units(units)
        for u in units:
            assert u.done.is_set()  # no hang: resolved on arrival
            assert u.completion.error.startswith("drained:")
            assert u.state == "drained"

    def test_submit_after_force_drain_resolves_drained(self):
        sched = ServeScheduler()
        sched.force_drain()
        u = _unit(debate="late")
        sched.submit_units([u])
        assert u.done.is_set()
        assert u.completion.error.startswith("drained:")

    def test_ttft_measured_from_admission_not_thread_start(self):
        """The executor queue wait is latency the client pays; the
        reported ttft_s must include it (run_debate threads t0 =
        accept_t through the Submission probe)."""
        serve_mod.configure(max_dispatch_batch=1)
        sched = ServeScheduler()
        gate.install(sched)
        pump = EnginePump(sched)
        pump.start()
        try:
            sched.try_admit("t0", "interactive", "d1", 100)
            accept_t = time.monotonic() - 30.0  # admitted 30s "ago"
            payload = run_debate(
                {
                    "tenant": "t0",
                    "tier": "interactive",
                    "spec": SPEC,
                    "models": ["mock://agree"],
                    "round": 1,
                },
                sched,
                debate_id="d1",
                accept_t=accept_t,
            )
        finally:
            sched.stop()
            gate.uninstall()
            pump.join(timeout=5)
        assert payload["ttft_s"] >= 30.0


class TestTraceScopes:
    """ISSUE 14 satellite: per-debate trace scopes + daemon-safe
    resets (the one-invocation-one-round assumption unbaked)."""

    def test_scoped_minting_no_collision(self):
        a1 = obs_mod.trace.mint_trace(1, scope="debate-a")
        b1 = obs_mod.trace.mint_trace(1, scope="debate-b")
        a2 = obs_mod.trace.mint_trace(2, scope="debate-a")
        assert a1 != b1  # same round, different debates: distinct ids
        assert a1.split("-")[-1] == a2.split("-")[-1]  # stable suffix
        # Deterministic per scope: a fresh scope counter restarts.
        obs_mod.trace.reset_scope("debate-a")
        assert obs_mod.trace.mint_trace(1, scope="debate-a") == a1

    def test_scoped_minting_does_not_reset_other_scopes(self):
        obs_mod.trace.reset()
        obs_mod.trace.mint_trace(1, scope="a")
        obs_mod.trace.mint_trace(1, scope="b")
        second_a = obs_mod.trace.mint_trace(1, scope="a")
        # Scope b minting did not reset scope a's counter.
        assert second_a.startswith("tr-001-02-")

    def test_unscoped_minting_unchanged(self):
        """The CLI path's exact-id pins survive: no scope = the
        process-wide counter and the classic format."""
        obs_mod.trace.reset()
        assert obs_mod.trace.mint_trace(3) == "tr-003-01"
        assert obs_mod.trace.mint_trace(3) == "tr-003-02"

    def test_ambient_is_thread_local(self):
        obs_mod.trace.set_ambient("tr-main", "")
        seen = {}

        def other():
            seen["before"] = obs_mod.trace.get_ambient()
            obs_mod.trace.set_ambient("tr-other", "s")
            seen["after"] = obs_mod.trace.get_ambient()

        th = threading.Thread(target=other)
        th.start()
        th.join()
        assert seen["before"] == ("", "")  # fresh thread: clean ambient
        assert seen["after"] == ("tr-other", "s")
        assert obs_mod.trace.get_ambient() == ("tr-main", "")
        obs_mod.trace.set_ambient("", "")

    def test_two_interleaved_concurrent_rounds(self):
        """The regression ISSUE 14 names: two debates run CONCURRENTLY
        in one process — no trace-id collision, each debate's span ids
        embed its own trace, and neither debate's counters are reset by
        the other (no cross-debate counter reset)."""
        serve_mod.configure(max_dispatch_batch=1)
        sched = ServeScheduler()
        gate.install(sched)
        pump = EnginePump(sched)
        pump.start()
        results = {}

        def one(name):
            sched.try_admit(name, "interactive", name, 100)
            results[name] = run_debate(
                {
                    "tenant": name,
                    "tier": "interactive",
                    "spec": SPEC,
                    "models": ["mock://critic?v=1", "mock://critic?v=2"],
                    "round": 1,
                },
                sched,
                debate_id=name,
            )

        try:
            threads = [
                threading.Thread(target=one, args=(n,), daemon=True)
                for n in ("da", "db")
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
                assert not t.is_alive()
        finally:
            sched.stop()
            gate.uninstall()
            pump.join(timeout=5)
        ra, rb = results["da"], results["db"]
        assert ra["trace_id"] != rb["trace_id"]
        for payload in (ra, rb):
            for r in payload["results"]:
                assert r["error"] is None
                assert r["span_id"].startswith(payload["trace_id"] + "/s")
        # No cross-debate counter reset: the process stats saw BOTH
        # debates accumulate (a per-invocation reset mid-serve would
        # have zeroed the first debate's counts).
        snap = serve_mod.snapshot()
        assert snap["completed_debates"] == 2
        assert snap["units_completed"] == 4


class TestBreakersInDaemon:
    """ISSUE 14 satellite: per-process breakers stay authoritative
    across successive rounds in one daemon process; snapshots ride the
    per-debate result at round commit."""

    def _debate(self, sched, name, models):
        sched.try_admit("t0", "interactive", name, 100)
        return run_debate(
            {
                "tenant": "t0",
                "tier": "interactive",
                "spec": SPEC,
                "models": models,
                "round": 1,
            },
            sched,
            debate_id=name,
        )

    def test_open_circuit_skips_across_rounds_one_process(self):
        breakers = breaker_mod.default_registry()
        breakers.configure(threshold=1, cooldown_s=3600.0)
        serve_mod.configure(max_dispatch_batch=1)
        sched = ServeScheduler()
        gate.install(sched)
        pump = EnginePump(sched)
        pump.start()
        try:
            r1 = self._debate(
                sched, "d1", ["mock://error", "mock://critic"]
            )
            # Round 1 opened the circuit (threshold 1); the snapshot
            # rides the result payload at round commit.
            assert r1["breakers"]["mock://error"]["state"] == "open"
            from adversarial_spec_tpu.engine import dispatch

            inner = dispatch.cached_engines()[0]
            calls_before = dict(inner._calls)
            r2 = self._debate(
                sched, "d2", ["mock://error", "mock://critic"]
            )
            # Round 2 in the SAME process: the failing model degraded
            # with ZERO engine attempts (no stale half-open probe — the
            # cooldown has not elapsed).
            assert "circuit open" in r2["results"][0]["error"]
            assert inner._calls.get("mock://error", 0) == calls_before.get(
                "mock://error", 0
            )
            assert r2["results"][1]["error"] is None
        finally:
            sched.stop()
            gate.uninstall()
            pump.join(timeout=5)

    def test_probe_not_leaked_between_tenants(self):
        """One half-open probe at a time, registry-wide: tenant A's
        in-flight probe means tenant B's request for the same model is
        degraded, not admitted as a second probe."""
        clock = [0.0]
        reg = breaker_mod.BreakerRegistry(
            threshold=1, cooldown_s=10.0, clock=lambda: clock[0]
        )
        reg.record("m", ok=False)
        assert reg.breaker("m").state == "open"
        clock[0] = 11.0
        assert reg.allow("m") is True  # tenant A's probe admitted
        assert reg.allow("m") is False  # tenant B must wait, not probe


@pytest.mark.chaos
class TestDaemonSocket:
    """The deterministic mock-engine daemon smoke (tier-1, chaos
    marker): a REAL unix socket, a real storm, the real drain."""

    def _start(self, tmp_path):
        sock = str(tmp_path / "serve.sock")
        ready = threading.Event()
        daemon = ServeDaemon(sock, sessions_dir=str(tmp_path / "sessions"))
        th = threading.Thread(
            target=lambda: asyncio.run(daemon.run(ready=ready)), daemon=True
        )
        th.start()
        assert ready.wait(10), "daemon did not come up"
        return daemon, th, sock

    def test_lifecycle_smoke(self, tmp_path):
        serve_mod.configure(max_queue_depth=8, max_backlog_tokens=10**6)
        daemon, th, sock = self._start(tmp_path)
        client = ServeClient(sock)
        try:
            assert client.ping()["event"] == "pong"
            rid = client.submit_debate(
                SPEC,
                ["mock://critic?v=1", "mock://agree"],
                stream=True,
            )
            evs = client.collect(rid, timeout_s=20)
            kinds = [e["event"] for e in evs]
            assert kinds[0] == "accepted" and kinds[-1] == "result"
            assert "stream" in kinds  # per-token transport delivered
            res = evs[-1]
            assert res.get("error") is None
            assert [r["agreed"] for r in res["results"]] == [False, True]
            assert res["ttft_s"] >= 0.0
            stats = client.stats()
            assert stats["serve"]["completed_debates"] == 1
            assert client.check()["ok"] is True
        finally:
            client.drain()
            client.close()
            th.join(timeout=15)
            assert not th.is_alive()
        assert daemon.drain_report["clean_exit"] is True

    def test_stats_op_exposes_admission_pressure(self, tmp_path):
        """The stats op carries the admission ledger's live pressure
        view over the wire — the ``pressure_snapshot`` the autoscaler
        reads in-process (backlog tokens, brownout, capacity) plus the
        per-tier queue depths already in ``scheduler`` — so the replay
        harness and external scrapers see the same numbers the
        scheduler sheds on."""
        serve_mod.configure(max_queue_depth=8, max_backlog_tokens=10**6)
        daemon, th, sock = self._start(tmp_path)
        client = ServeClient(sock)
        try:
            rid = client.submit_debate(
                SPEC, ["mock://critic?v=1", "mock://agree"]
            )
            client.collect(rid, timeout_s=20)
            stats = client.stats()
            pressure = stats["pressure"]
            for key in (
                "backlog_tokens",
                "prefill_backlog_tokens",
                "decode_backlog_tokens",
                "capacity_tokens",
                "brownout",
                "draining",
            ):
                assert key in pressure, key
            assert pressure["capacity_tokens"] == 10**6
            assert pressure["brownout"] is False
            assert pressure["draining"] is False
            # Per-tier queue depths ride in the scheduler snapshot.
            assert set(stats["scheduler"]["queued_units"]) >= {
                "interactive",
                "batch",
            }
        finally:
            client.drain()
            client.close()
            th.join(timeout=15)

    def test_overload_storm_sheds_typed_zero_loss(self, tmp_path):
        """The tier-1 slice of chaos_run --overload: open-loop burst
        past the caps → typed sheds, zero accepted loss, brownout,
        interactive admitted in full, invariants clean."""
        serve_mod.configure(
            max_queue_depth=2, max_backlog_tokens=16000
        )
        daemon, th, sock = self._start(tmp_path)
        client = ServeClient(sock, timeout_s=60)
        try:
            submitted = []
            for k in range(12):
                submitted.append(
                    (
                        client.submit_debate(
                            SPEC,
                            ["mock://critic?v=1", "mock://critic?v=2"],
                            tenant=f"b{k % 2}",
                            tier="batch",
                            max_new_tokens=1536,
                        ),
                        "batch",
                    )
                )
                if k < 4:
                    submitted.append(
                        (
                            client.submit_debate(
                                SPEC,
                                ["mock://agree"],
                                tenant=f"i{k % 2}",
                                tier="interactive",
                                max_new_tokens=64,
                            ),
                            "interactive",
                        )
                    )
            shed = {"batch": 0, "interactive": 0}
            accepted = {"batch": 0, "interactive": 0}
            lost = 0
            for rid, tier in submitted:
                evs = client.collect(rid, timeout_s=60)
                if evs[0]["event"] == "accepted":
                    accepted[tier] += 1
                    last = evs[-1]
                    if last["event"] != "result" or last.get("error") or any(
                        r["error"] for r in last["results"]
                    ):
                        lost += 1
                else:
                    assert evs[-1]["event"] == "shed"
                    assert evs[-1]["reason"] in protocol.SHED_REASONS
                    assert isinstance(
                        evs[-1]["retry_after_s"], (int, float)
                    )
                    shed[tier] += 1
            assert lost == 0  # zero accepted-request loss
            assert accepted["interactive"] == 4  # never shed
            assert shed["batch"] > 0  # batch starved first
            snap = serve_mod.snapshot()
            assert snap["brownout_entries"] >= 1
            assert client.check()["ok"] is True
            assert (
                accepted["batch"]
                + accepted["interactive"]
                + shed["batch"]
                + shed["interactive"]
                == len(submitted)
            )
        finally:
            client.drain()
            client.close()
            th.join(timeout=15)

    def test_refill_and_stats_ops(self, tmp_path):
        serve_mod.configure(tenant_quota_tokens=50)
        daemon, th, sock = self._start(tmp_path)
        client = ServeClient(sock)
        try:
            rid = client.submit_debate(SPEC, ["mock://critic"], tenant="q0")
            last = client.collect(rid, timeout_s=20)[-1]
            assert last["event"] == "result"
            # The round charged more than the 50-token quota: the next
            # debate sheds until a refill lands.
            shed = client.collect(
                client.submit_debate(SPEC, ["mock://critic"], tenant="q0"),
                timeout_s=20,
            )[-1]
            assert shed["event"] == "shed" and shed["reason"] == "quota"
            refill = client.refill("q0", 100000)
            assert refill["quota_remaining"] > 0
            ok = client.collect(
                client.submit_debate(SPEC, ["mock://critic"], tenant="q0"),
                timeout_s=20,
            )[-1]
            assert ok["event"] == "result" and not ok.get("error")
        finally:
            client.drain()
            client.close()
            th.join(timeout=15)

    def test_malformed_requests_answered_not_fatal(self, tmp_path):
        daemon, th, sock = self._start(tmp_path)
        client = ServeClient(sock)
        try:
            client.sock.sendall(b"not json at all\n")
            ev = client.recv(timeout_s=10)
            assert ev["event"] == "error"
            bad = client.call({"op": "debate", "tenant": "t0"})
            assert bad["event"] == "error"
            assert client.ping()["event"] == "pong"  # daemon unharmed
        finally:
            client.drain()
            client.close()
            th.join(timeout=15)


class TestCliServe:
    def test_parser_accepts_serve_flags(self):
        from adversarial_spec_tpu import cli

        parser = cli.create_parser()
        args = parser.parse_args(
            [
                "serve",
                "--socket",
                "/tmp/x.sock",
                "--serve-queue-depth",
                "3",
                "--serve-backlog-tokens",
                "9999",
                "--serve-quota-tokens",
                "100",
                "--serve-drain-deadline-s",
                "1.5",
                "--serve-ttft-slo-ms",
                "250",
                "--drain-report",
                "/tmp/report.json",
            ]
        )
        assert args.action == "serve"
        assert args.serve_queue_depth == 3
        assert args.serve_drain_deadline_s == 1.5

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("ADVSPEC_SERVE_QUEUE_DEPTH", "17")
        monkeypatch.setenv("ADVSPEC_SERVE_BACKLOG_TOKENS", "12345")
        monkeypatch.setenv("ADVSPEC_SERVE_QUOTA_TOKENS", "77")
        monkeypatch.setenv("ADVSPEC_SERVE_DRAIN_DEADLINE_S", "2.5")
        monkeypatch.setenv("ADVSPEC_SERVE_TTFT_SLO_MS", "300")
        assert serve_mod.env_queue_depth() == 17
        assert serve_mod.env_backlog_tokens() == 12345
        assert serve_mod.env_quota_tokens() == 77
        assert serve_mod.env_drain_deadline_s() == 2.5
        assert serve_mod.env_ttft_slo_ms() == 300.0

    def test_env_garbage_falls_back(self, monkeypatch):
        monkeypatch.setenv("ADVSPEC_SERVE_QUEUE_DEPTH", "lots")
        monkeypatch.setenv("ADVSPEC_SERVE_DRAIN_DEADLINE_S", "-3")
        assert serve_mod.env_queue_depth() == serve_mod.DEFAULT_QUEUE_DEPTH
        assert serve_mod.env_drain_deadline_s() == 0.0


class TestServeTooling:
    def test_obs_dump_renders_tenant_column_and_shed_rows(self):
        from adversarial_spec_tpu.obs.events import event_to_dict
        from tools.obs_dump import occupancy_timeline, summarize

        events = [
            event_to_dict(
                1,
                obs_mod.ServeEvent(
                    op="accepted", tenant="tA", tier="interactive",
                    debate="d00001", tokens=100, backlog_tokens=100,
                ),
            ),
            event_to_dict(
                2,
                obs_mod.ServeEvent(
                    op="running", tenant="tA", tier="interactive",
                    debate="d00001", index=0, backlog_tokens=100,
                ),
            ),
            event_to_dict(
                3,
                obs_mod.StepEvent(kind="decode", n_live=1),
            ),
            event_to_dict(
                4,
                obs_mod.ServeEvent(
                    op="shed", tenant="tB", tier="batch", debate="d00002",
                    reason="brownout", backlog_tokens=900,
                ),
            ),
            event_to_dict(
                5,
                obs_mod.ServeEvent(
                    op="preempted", tenant="tC", tier="batch",
                    debate="d00003", index=1, reason="tier_pressure",
                    backlog_tokens=900,
                ),
            ),
        ]
        for e in events:
            assert validate_event(e) == [], e
        timeline = occupancy_timeline(events)
        assert "ten=tA" in timeline  # the per-tenant column
        assert "serve:shed" in timeline and "(brownout)" in timeline
        assert "serve:preempted" in timeline
        assert "backlog=900" in timeline
        summary = summarize(events)
        assert "1 typed load-shed refusal(s): brownout=1" in summary
        assert "1 batch unit(s) preempted" in summary

    def test_bench_trend_validates_serve_schema(self, tmp_path):
        from tools.bench_trend import validate_bench_file

        good = {
            "metric": "serve_capacity_debates_per_s",
            "value": 100.0,
            "unit": "debates/s",
            "platform": "cpu",
            "shed_fraction": 0.5,
            "brownout_transitions": 2,
            "capacity": {"debates_per_s": 100.0},
        }
        p = tmp_path / "BENCH_serve.json"
        p.write_text(json.dumps(good))
        row, problems = validate_bench_file(p)
        assert problems == []
        assert row["shed_fraction"] == 0.5
        assert row["brownout_transitions"] == 2
        # Dropping any serve-schema field is a violation, not a silent
        # trend-table hole.
        for missing in ("shed_fraction", "brownout_transitions", "capacity"):
            bad = {k: v for k, v in good.items() if k != missing}
            p.write_text(json.dumps(bad))
            row, problems = validate_bench_file(p)
            assert problems, f"missing {missing} not flagged"

    def test_lifecycle_live_fire_pin(self):
        """Stripping the serve release surgery fires GL-LIFECYCLE on
        the real source — and the committed source is clean (the
        machine-3 registration is live, not decorative)."""
        from pathlib import Path

        from tools.graftlint.core import lint_sources

        path = "adversarial_spec_tpu/serve/sched.py"
        src = (Path(__file__).resolve().parent.parent / path).read_text()
        assert lint_sources({path: src}, rules=["GL-LIFECYCLE"]) == []
        assert "self._release_unit(" in src
        mutated = src.replace(
            "self._release_unit(", "(lambda *a, **k: None)("
        )
        findings = lint_sources({path: mutated}, rules=["GL-LIFECYCLE"])
        assert findings, (
            "stripping _release_unit produced no GL-LIFECYCLE finding "
            "— the serve machine is unguarded"
        )
        msgs = " ".join(f.message for f in findings)
        assert "ServeScheduler" in msgs

    def test_estimate_tokens_scales(self):
        small = estimate_tokens(
            ChatRequest(model="m", system="s", user="u"),
            SamplingParams(max_new_tokens=10),
        )
        big = estimate_tokens(
            ChatRequest(model="m", system="s" * 4000, user="u" * 4000),
            SamplingParams(max_new_tokens=1000),
        )
        assert big > small > 0
