"""Session persistence tests (reference analog: tests/test_session.py —
exact filenames, defaults, sort order, traversal guard)."""

import json

import pytest

from adversarial_spec_tpu.debate.session import (
    InvalidSessionId,
    SessionState,
    save_checkpoint,
)
from adversarial_spec_tpu.debate import session as session_mod


class TestSessionState:
    def test_save_load_roundtrip(self):
        s = SessionState(
            session_id="proj-1",
            spec="# Spec",
            round=4,
            doc_type="tech",
            models=["mock://critic"],
            focus="security",
            persona="qa-engineer",
            preserve_intent=True,
            history=[{"round": 3, "all_agreed": False, "models": {}}],
        )
        path = s.save()
        assert path.name == "proj-1.json"
        loaded = SessionState.load("proj-1")
        assert loaded.spec == "# Spec"
        assert loaded.round == 4
        assert loaded.doc_type == "tech"
        assert loaded.models == ["mock://critic"]
        assert loaded.focus == "security"
        assert loaded.preserve_intent is True
        assert loaded.history[0]["round"] == 3

    def test_save_sets_timestamps(self):
        s = SessionState(session_id="t")
        s.save()
        assert s.created_at > 0
        assert s.updated_at >= s.created_at
        created = s.created_at
        s.save()
        assert s.created_at == created  # created_at stable across saves

    def test_load_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            SessionState.load("absent")

    def test_path_traversal_rejected(self):
        for bad in ("../evil", "a/b", "", "x\\y", "a b"):
            with pytest.raises(InvalidSessionId):
                SessionState.save(SessionState(session_id=bad))
            if bad:
                with pytest.raises(InvalidSessionId):
                    SessionState.load(bad)

    def test_load_ignores_unknown_fields(self):
        d = session_mod.SESSIONS_DIR
        d.mkdir(parents=True)
        (d / "x.json").write_text(
            json.dumps({"session_id": "x", "spec": "s", "bogus": 1})
        )
        assert SessionState.load("x").spec == "s"

    def test_list_sessions_sorted_most_recent_first(self):
        a = SessionState(session_id="a")
        a.save()
        b = SessionState(session_id="b")
        b.save()
        b.updated_at = a.updated_at + 100
        (session_mod.SESSIONS_DIR / "b.json").write_text(
            json.dumps(
                {"session_id": "b", "updated_at": b.updated_at, "round": 2}
            )
        )
        sessions = SessionState.list_sessions()
        assert [s["session_id"] for s in sessions] == ["b", "a"]

    def test_list_sessions_empty_dir(self):
        assert SessionState.list_sessions() == []

    def test_list_sessions_skips_corrupt(self):
        d = session_mod.SESSIONS_DIR
        d.mkdir(parents=True)
        (d / "bad.json").write_text("{not json")
        SessionState(session_id="good").save()
        assert [s["session_id"] for s in SessionState.list_sessions()] == [
            "good"
        ]


class TestCheckpoints:
    def test_checkpoint_filename_without_session(self):
        p = save_checkpoint("spec text", 3)
        assert p.name == "round-3.md"
        assert p.read_text() == "spec text"

    def test_checkpoint_filename_with_session(self):
        p = save_checkpoint("s", 1, session_id="proj")
        assert p.name == "proj-round-1.md"

    def test_checkpoint_session_id_validated(self):
        with pytest.raises(InvalidSessionId):
            save_checkpoint("s", 1, session_id="../evil")


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors
    (tools/mutation_run.py; each assertion names the mutant it kills)."""

    def test_storage_constants_pinned(self):
        """Kills path-component string mutants on SESSIONS_DIR /
        CHECKPOINTS_DIR: on-disk locations are a compatibility contract
        (a mutated path would orphan every existing session). Pinned via
        source text because conftest patches the live constants to
        tmp dirs for isolation."""
        from pathlib import Path

        src = Path(session_mod.__file__).read_text()
        assert (
            'Path.home() / ".config" / "adversarial-spec-tpu" / "sessions"'
            in src
        )
        assert 'CHECKPOINTS_DIR = Path(".adversarial-spec-checkpoints")' in src

    def test_fresh_session_defaults(self):
        """Kills default mutants: round 1->2, doc_type XX,
        preserve_intent flip."""
        s = SessionState(session_id="d")
        assert s.round == 1
        assert s.doc_type == "generic"
        assert s.preserve_intent is False
        assert s.models == [] and s.history == []

    def test_invalid_id_message_names_the_rules(self):
        with pytest.raises(
            InvalidSessionId, match="only letters, digits"
        ):
            SessionState(session_id="../evil").save()

    def test_save_creates_nested_dirs_and_is_idempotent(self, tmp_path):
        """Kills the mkdir(parents=..., exist_ok=...) flag flips."""
        nested = tmp_path / "deep" / "nested" / "sessions"
        s = SessionState(session_id="n")
        p1 = s.save(sessions_dir=nested)
        p2 = s.save(sessions_dir=nested)  # exist_ok must hold
        assert p1 == p2 and p1.is_file()

    def test_list_sessions_summary_schema(self, tmp_path):
        """Kills the summary dict-key/default mutants: the schema is the
        CLI `sessions` action's output contract, incl. fallbacks for
        files written by hand or by older versions."""
        (tmp_path / "bare.json").write_text("{}")
        full = {
            "session_id": "full",
            "round": 7,
            "doc_type": "prd",
            "models": ["tpu://m"],
            "updated_at": 99.5,
        }
        (tmp_path / "full.json").write_text(json.dumps(full))
        out = SessionState.list_sessions(sessions_dir=tmp_path)
        assert out[0] == full  # exact keys AND values
        assert out[1] == {
            "session_id": "bare",  # falls back to the file stem
            "round": 1,
            "doc_type": "generic",
            "models": [],
            "updated_at": 0.0,
        }

    def test_checkpoint_creates_nested_dirs_and_overwrites(self, tmp_path):
        nested = tmp_path / "a" / "b"
        p1 = save_checkpoint("v1", 1, checkpoints_dir=nested)
        p2 = save_checkpoint("v2", 1, checkpoints_dir=nested)
        assert p1 == p2
        assert p2.read_text() == "v2"
