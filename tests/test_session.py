"""Session persistence tests (reference analog: tests/test_session.py —
exact filenames, defaults, sort order, traversal guard)."""

import json

import pytest

from adversarial_spec_tpu.debate.session import (
    InvalidSessionId,
    SessionState,
    save_checkpoint,
)
from adversarial_spec_tpu.debate import session as session_mod


class TestSessionState:
    def test_save_load_roundtrip(self):
        s = SessionState(
            session_id="proj-1",
            spec="# Spec",
            round=4,
            doc_type="tech",
            models=["mock://critic"],
            focus="security",
            persona="qa-engineer",
            preserve_intent=True,
            history=[{"round": 3, "all_agreed": False, "models": {}}],
        )
        path = s.save()
        assert path.name == "proj-1.json"
        loaded = SessionState.load("proj-1")
        assert loaded.spec == "# Spec"
        assert loaded.round == 4
        assert loaded.doc_type == "tech"
        assert loaded.models == ["mock://critic"]
        assert loaded.focus == "security"
        assert loaded.preserve_intent is True
        assert loaded.history[0]["round"] == 3

    def test_save_sets_timestamps(self):
        s = SessionState(session_id="t")
        s.save()
        assert s.created_at > 0
        assert s.updated_at >= s.created_at
        created = s.created_at
        s.save()
        assert s.created_at == created  # created_at stable across saves

    def test_load_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            SessionState.load("absent")

    def test_path_traversal_rejected(self):
        for bad in ("../evil", "a/b", "", "x\\y", "a b"):
            with pytest.raises(InvalidSessionId):
                SessionState.save(SessionState(session_id=bad))
            if bad:
                with pytest.raises(InvalidSessionId):
                    SessionState.load(bad)

    def test_load_ignores_unknown_fields(self):
        d = session_mod.SESSIONS_DIR
        d.mkdir(parents=True)
        (d / "x.json").write_text(
            json.dumps({"session_id": "x", "spec": "s", "bogus": 1})
        )
        assert SessionState.load("x").spec == "s"

    def test_list_sessions_sorted_most_recent_first(self):
        a = SessionState(session_id="a")
        a.save()
        b = SessionState(session_id="b")
        b.save()
        b.updated_at = a.updated_at + 100
        (session_mod.SESSIONS_DIR / "b.json").write_text(
            json.dumps(
                {"session_id": "b", "updated_at": b.updated_at, "round": 2}
            )
        )
        sessions = SessionState.list_sessions()
        assert [s["session_id"] for s in sessions] == ["b", "a"]

    def test_list_sessions_empty_dir(self):
        assert SessionState.list_sessions() == []

    def test_list_sessions_skips_corrupt(self):
        d = session_mod.SESSIONS_DIR
        d.mkdir(parents=True)
        (d / "bad.json").write_text("{not json")
        SessionState(session_id="good").save()
        assert [s["session_id"] for s in SessionState.list_sessions()] == [
            "good"
        ]


class TestCheckpoints:
    def test_checkpoint_filename_without_session(self):
        p = save_checkpoint("spec text", 3)
        assert p.name == "round-3.md"
        assert p.read_text() == "spec text"

    def test_checkpoint_filename_with_session(self):
        p = save_checkpoint("s", 1, session_id="proj")
        assert p.name == "proj-round-1.md"

    def test_checkpoint_session_id_validated(self):
        with pytest.raises(InvalidSessionId):
            save_checkpoint("s", 1, session_id="../evil")
