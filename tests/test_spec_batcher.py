"""Per-slot prompt-lookup speculation on the paged serving path.

Correctness contracts (ISSUE 6):
- greedy output through the ContinuousBatcher is BYTE-IDENTICAL spec-on
  vs spec-off — across the pipelined and legacy drive loops, tp=1 and
  tp=2 meshes, prefix cache on and off, and every draft width γ
  (acceptance only changes how many tokens emerge per device program,
  never which tokens);
- the page pool survives rollback: ``check_invariants`` holds after
  EVERY speculative step, rejected draft pages return to the pool, and
  pages shared with the prefix cache only lose the speculating row's
  reference;
- a fault mid-verify evicts ONLY the speculating slot, frees both its
  committed and in-flight draft pages, and the auto-dumped flight
  recorder JSONL reconstructs the eviction;
- the γ knob lives in engine/spec.py (process config, CLI ``--gamma``),
  reconfigurable without a reimport, validated at the knob.
"""

import io
import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import spec as spec_mod
from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.engine.kvcache import PageAllocator
from adversarial_spec_tpu.engine.scheduler import (
    ContinuousBatcher,
    SchedRequest,
)
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


@pytest.fixture(autouse=True)
def _spec_defaults():
    """Every test starts from the process defaults and leaves them."""
    spec_mod.configure(enabled=True, gamma=spec_mod.DEFAULT_GAMMA)
    spec_mod.reset_stats()
    yield
    spec_mod.configure(enabled=True, gamma=spec_mod.DEFAULT_GAMMA)
    spec_mod.reset_stats()


def _repetitive_prompt(n, period=7, lo=5):
    """Tiled token pattern: recurring bigrams for prompt-lookup to
    draft from (the [SPEC] revision shape — near-copies of earlier
    context)."""
    return [lo + (i % period) for i in range(n)]


def _drain(params, cfg, prompts, budgets, *, eos=(), **kw):
    timeout_s = kw.pop("timeout_s", 0.0)
    b = ContinuousBatcher(
        params,
        cfg,
        max_batch=kw.pop("max_batch", 2),
        max_new_cap=max(budgets),
        eos_ids=list(eos),
        **kw,
    )
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        b.submit(
            SchedRequest(req_id=i, prompt_ids=list(p), max_new_tokens=n)
        )
    results = b.run_all(timeout_s)
    return b, {r.req_id: r.tokens.tolist() for r in results}, results


class TestSpecConfig:
    def test_gamma_validated_at_the_knob(self):
        with pytest.raises(ValueError, match="ADVSPEC_GAMMA must be >= 1"):
            spec_mod.configure(gamma=0)

    def test_configure_retunes_without_reimport(self):
        spec_mod.configure(gamma=3, enabled=False)
        assert spec_mod.config().gamma == 3
        assert spec_mod.config().enabled is False
        snap = spec_mod.snapshot()
        assert snap["gamma"] == 3 and snap["enabled"] is False

    def test_env_gamma_validated(self, monkeypatch):
        monkeypatch.setenv("ADVSPEC_GAMMA", "0")
        with pytest.raises(ValueError, match="ADVSPEC_GAMMA must be >= 1"):
            spec_mod.env_gamma()

    def test_speculative_module_snapshot_constant(self):
        # The dense path's import-time GAMMA snapshot still validates
        # (it IS env_gamma at import) and stays an int ≥ 1.
        from adversarial_spec_tpu.engine.speculative import GAMMA

        assert GAMMA >= 1

    def test_reenable_reclamps_gamma_vs_cap(self, tiny_model):
        """Review regression: reconfigure_speculative(enabled=True) on a
        batcher the constructor degraded to plain decode (cap <= 1
        leaves γ unclamped) must re-walk the γ-vs-cap clamp instead of
        re-arming speculation with a span wider than the output
        buffer."""
        params, cfg = tiny_model
        b = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=1,
            speculative=True, gamma=8,
        )
        assert b.speculative is False
        b.reconfigure_speculative(enabled=True)
        assert b.speculative is False, "1-token cap cannot fit a span"

    def test_dense_generate_follows_process_config(
        self, tiny_model, monkeypatch
    ):
        """Review regression: dense generate() used to read
        ADVSPEC_SPECULATIVE from the env directly and freeze γ at
        import, so CLI --no-speculative/--gamma (which only call
        spec.configure()) never reached the dense fallback path."""
        import adversarial_spec_tpu.engine.speculative as sp_mod

        params, cfg = tiny_model
        real = sp_mod.speculative_decode_steps
        seen_gammas = []

        def spy(*a, **k):
            seen_gammas.append(k.get("gamma"))
            return real(*a, **k)

        monkeypatch.setattr(sp_mod, "speculative_decode_steps", spy)
        prompt = _repetitive_prompt(24)
        kw = dict(max_new_tokens=16, eos_ids=[], greedy=True)
        spec_mod.configure(enabled=False)
        off = generate(params, cfg, [prompt], **kw)
        assert not seen_gammas, "configure(enabled=False) must reach it"
        spec_mod.configure(enabled=True, gamma=4)
        on = generate(params, cfg, [prompt], **kw)
        assert seen_gammas == [4], "configure(gamma=) must reach it"
        np.testing.assert_array_equal(on.tokens, off.tokens)

    def test_reconfigure_refuses_resident_rows(self, tiny_model):
        params, cfg = tiny_model
        b = ContinuousBatcher(params, cfg, max_batch=1, max_new_cap=4)
        b._slot_req[0] = SchedRequest(
            req_id=0, prompt_ids=[1], max_new_tokens=1
        )
        with pytest.raises(RuntimeError, match="resident rows"):
            b.reconfigure_speculative(enabled=False)

    def test_reconfigure_between_drains(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(40)]
        b, toks1, _ = _drain(
            params, cfg, prompts, [16], max_batch=1, speculative=True,
            gamma=4,
        )
        b.reconfigure_speculative(enabled=False)
        for i, p in enumerate(prompts):
            b.submit(
                SchedRequest(req_id=i, prompt_ids=p, max_new_tokens=16)
            )
        results2 = b.run_all()
        toks2 = {r.req_id: r.tokens.tolist() for r in results2}
        assert toks1 == toks2  # greedy parity across the flip
        # Review regression: the handoff must reset the slot's spec
        # telemetry even with speculation now OFF — round 2's results
        # must not inherit round 1's counts ('all zero with
        # --no-speculative').
        assert all(r.spec_steps == 0 for r in results2)
        assert all(r.spec_drafted == 0 for r in results2)


class TestBatcherSpecParity:
    def test_spec_on_off_greedy_parity_with_acceptance(self, tiny_model):
        # max_batch=2 with 4 requests: co-residency AND queue churn,
        # on the (B=2, cap=16, γ=4) program shape every parity test in
        # this class shares (cap/B are static args — each distinct pair
        # compiles a fresh verify program).
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(60 + i) for i in range(4)]
        budgets = [16] * 4
        spec_mod.reset_stats()
        _, on, _ = _drain(
            params, cfg, prompts, budgets, max_batch=2,
            speculative=True, gamma=4,
        )
        stats = spec_mod.stats
        assert stats.spec_steps > 0
        assert stats.accepted_tokens > 0, "workload must exercise accepts"
        assert stats.emitted_tokens > stats.spec_steps  # >1 token/step
        _, off, _ = _drain(
            params, cfg, prompts, budgets, max_batch=2, speculative=False,
        )
        assert on == off

    @pytest.mark.slow  # batcher-vs-dense is also pinned (cheaper) by
    # test_gamma_clamps_to_output_cap and the slot-churn test
    def test_matches_dense_generate_reference(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(48), _repetitive_prompt(31)]
        _, on, _ = _drain(
            params, cfg, prompts, [16, 16], speculative=True, gamma=4,
        )
        for i, p in enumerate(prompts):
            ref = generate(
                params, cfg, [p], max_new_tokens=16, eos_ids=[],
                greedy=True, speculative=False,
            )
            np.testing.assert_array_equal(
                on[i], ref.tokens[0, : ref.n_generated[0]],
                err_msg=f"req {i}",
            )

    def test_parity_with_prefix_cache(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(80)] * 2  # identical → shared blocks
        kw = dict(speculative=True, gamma=4, page_size=16)
        _, cached, r1 = _drain(
            params, cfg, prompts, [16, 16], prefix_cache=True, **kw
        )
        _, plain, _ = _drain(
            params, cfg, prompts, [16, 16], prefix_cache=False, **kw
        )
        assert cached == plain
        assert r1[1].cached_tokens > 0  # the cache actually engaged

    def test_legacy_loop_parity(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(52), _repetitive_prompt(33)]
        kw = dict(speculative=True, gamma=4)
        _, pipelined, _ = _drain(
            params, cfg, prompts, [16, 16], interleave=True, **kw
        )
        _, legacy, _ = _drain(
            params, cfg, prompts, [16, 16], interleave=False, **kw
        )
        assert pipelined == legacy

    @pytest.mark.slow  # full sharded-program compile set; the cheaper
    # dp:1 mesh pin below keeps the on-mesh jit-signature class in
    # tier-1
    def test_tp2_mesh_parity(self, tiny_model):
        if len(jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [_repetitive_prompt(50), _repetitive_prompt(50 + 1)]
        _, ref, _ = _drain(
            params, cfg, prompts, [16, 16], speculative=False,
        )
        mesh = make_mesh({"tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            _, out, _ = _drain(
                sharded, cfg, prompts, [16, 16], speculative=True, gamma=4,
            )
        assert ref == out

    def test_verify_program_compiles_once_on_mesh(self, tiny_model):
        """Verify-drive regression: with mesh-committed params, the
        batcher's fresh (uncommitted) row-state arrays and step 1's
        mesh-committed donated outputs used to present two jit
        signatures for the same verify program — XLA compiled
        scheduler_spec_chunk twice on the engine's first paged spec
        drive (ctx_len/prev_tok/cur_len/n_emitted/active flipped
        UnspecifiedValue → NamedSharding between steps). Row state is
        now committed at creation; the retrace watch must see no
        seen-key recompile."""
        from adversarial_spec_tpu import obs
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        mesh = make_mesh({"dp": 1})
        sharded = shard_params(mesh, params)
        was_enabled = obs.config().enabled
        obs.configure(enabled=True)
        obs.retrace.clear()
        try:
            with mesh:
                # Minimal shapes: the pin is about jit SIGNATURES
                # (≥2 spec steps on mesh-sharded params), not workload.
                _drain(
                    sharded, cfg, [_repetitive_prompt(24)], [8],
                    max_batch=1, speculative=True, gamma=4,
                )
        finally:
            snap = obs.retrace.snapshot()
            obs.retrace.clear()
            obs.configure(enabled=was_enabled)
        spec_progs = {
            k: v for k, v in snap["programs"].items() if "spec" in k
        }
        assert spec_progs, "no speculative program dispatched"
        assert snap["unexpected_recompiles"] == 0, snap

    def test_gamma_sweep_parity(self, tiny_model):
        """Every draft width compiles its own verify program; none may
        change greedy tokens."""
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(44)]
        outs = {}
        for gamma in (1, 3, 8):
            _, outs[gamma], _ = _drain(
                params, cfg, prompts, [16], max_batch=1,
                speculative=True, gamma=gamma,
            )
        assert outs[1] == outs[3] == outs[8]

    def test_eos_parity_inside_span(self, tiny_model):
        """An EOS landing inside an accepted span must stop the row at
        the same token plain decode stops at."""
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(40)]
        _, probe, _ = _drain(
            params, cfg, prompts, [16], max_batch=1, speculative=False,
        )
        out = probe[0]
        if len(out) < 4:
            pytest.skip("probe output too short to pick a mid-run EOS")
        eos = out[len(out) // 2]
        kw = dict(max_batch=1, eos=[eos])
        _, on, _ = _drain(
            params, cfg, prompts, [16], speculative=True, gamma=4, **kw
        )
        _, off, _ = _drain(
            params, cfg, prompts, [16], speculative=False, **kw
        )
        assert on == off
        assert on[0][-1] == eos  # EOS kept, nothing after

    def test_gamma_clamps_to_output_cap(self, tiny_model):
        """Regression: max_new_cap smaller than γ+1 used to push the
        spec chunk's masked append window start negative, smashing the
        row's first tokens (found by the prefix-cache replay test's
        max_new_cap=8 batcher under the default γ=8). γ must clamp so
        the span fits the buffer; a 1-token cap degrades to plain
        decode."""
        params, cfg = tiny_model
        prompt = [((i * 7) % 400) + 3 for i in range(96)]
        b = ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8,
            speculative=True, gamma=8,
        )
        assert b.gamma == 7
        b.submit(
            SchedRequest(req_id=0, prompt_ids=list(prompt),
                         max_new_tokens=8)
        )
        [res] = b.run_all()
        ref = generate(
            params, cfg, [prompt], max_new_tokens=8, eos_ids=[],
            greedy=True, speculative=False,
        )
        np.testing.assert_array_equal(
            res.tokens, ref.tokens[0, : ref.n_generated[0]]
        )
        tiny = ContinuousBatcher(
            params, cfg, max_batch=1, max_new_cap=1,
            speculative=True, gamma=8,
        )
        assert tiny.speculative is False

    def test_sched_result_carries_spec_counts(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(48)]
        _, _, results = _drain(
            params, cfg, prompts, [16], max_batch=1,
            speculative=True, gamma=4,
        )
        r = results[0]
        assert r.spec_steps > 0
        assert r.spec_drafted >= r.spec_accepted >= 0
        _, _, results = _drain(
            params, cfg, prompts, [16], max_batch=1, speculative=False,
        )
        assert results[0].spec_steps == 0
        assert results[0].spec_drafted == 0


class TestSpecRollback:
    def test_truncate_releases_tail_pages(self):
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        a.extend(0, 10)  # 3 pages
        assert a.free_pages == 5
        released = a.truncate(0, 5)  # keep 2 pages
        assert len(released) == 1
        assert a.length(0) == 5
        assert a.covered_tokens(0) == 8
        assert a.free_pages == 6
        a.check_invariants()

    def test_truncate_validates_bounds(self):
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        a.extend(0, 6)
        with pytest.raises(ValueError):
            a.truncate(0, 7)
        with pytest.raises(ValueError):
            a.truncate(0, -1)

    def test_truncate_shared_page_keeps_cache_ref(self):
        """A draft tail page shared with the prefix cache loses only the
        sequence's hold — the copy-on-append boundary."""
        a = PageAllocator(8, 4)
        a.new_sequence(0)
        a.extend(0, 8)  # 2 pages
        tail = a.table(0)[1]
        a.cache_ref(tail)  # the cache holds the tail block too
        released = a.truncate(0, 4)
        assert released == [tail]
        assert a.refcount(tail) == 1  # cache hold survives
        assert a.free_pages == 6  # NOT back on the free list
        a.check_invariants()
        a.cache_unref(tail)
        assert a.free_pages == 7

    def test_rollback_happens_with_small_pages(self, tiny_model):
        """γ spanning multiple small pages: rejected drafts must release
        pages (rolled_back_pages > 0) and the pool must stay clean."""
        params, cfg = tiny_model
        spec_mod.reset_stats()
        # Same (B=2, cap=16, γ=7, page=4) shape as the fuzz's third
        # trial, so the verify program compiles once for both tests.
        b, _, results = _drain(
            params, cfg, [_repetitive_prompt(41)], [16], max_batch=2,
            speculative=True, gamma=7, page_size=4, prefix_cache=False,
            capacity_tokens=512,
        )
        assert all(r.error is None for r in results)
        assert spec_mod.stats.rolled_back_pages > 0
        b.allocator.check_invariants()
        assert b.allocator.free_pages == b.allocator.n_pages

    def test_invariants_after_every_spec_step_fuzz(
        self, tiny_model, monkeypatch
    ):
        """THE acceptance pin: check_invariants after EVERY speculative
        step (the instant the rollback ran), over a randomized workload
        with small pages, pool pressure, and the prefix cache engaged."""
        params, cfg = tiny_model
        checked = {"n": 0}
        orig = ContinuousBatcher._apply_spec_counts

        def checked_apply(self, counts_np, live_slots):
            orig(self, counts_np, live_slots)
            self.allocator.check_invariants()
            checked["n"] += 1

        monkeypatch.setattr(
            ContinuousBatcher, "_apply_spec_counts", checked_apply
        )
        rng = random.Random(0xD1CE)
        for trial in range(3):
            prompts = [
                _repetitive_prompt(
                    rng.randrange(20, 70), period=rng.randrange(3, 9)
                )
                for _ in range(4)
            ]
            # cap = max(budgets) is a STATIC jit arg — pin it to 16 so
            # the three trials recompile only per γ, not per trial.
            budgets = [rng.randrange(6, 17) for _ in prompts]
            budgets[0] = 16
            b, _, results = _drain(
                params, cfg, prompts, budgets, max_batch=2,
                speculative=True, gamma=[2, 5, 7][trial],
                page_size=4, capacity_tokens=512,
                prefix_cache=bool(trial % 2),
            )
            assert {r.req_id for r in results} == set(range(len(prompts)))
            if b.prefix_cache is not None:
                b.prefix_cache.clear()
            assert b.allocator.free_pages == b.allocator.n_pages
        assert checked["n"] > 0, "fuzz never exercised a speculative step"


class TestSpecChaos:
    def _arm(self, spec):
        from adversarial_spec_tpu.resilience import injector

        injector.install(
            injector.FaultInjector(injector.parse_chaos_spec(spec))
        )
        return injector

    def test_mid_verify_fault_evicts_only_speculating_slot(
        self, tiny_model, tmp_path
    ):
        """An injected fault on the spec dispatch seam: the named slot is
        evicted with its committed AND draft pages freed, the
        co-resident finishes with byte-identical tokens, and the
        auto-dumped JSONL reconstructs the eviction."""
        from adversarial_spec_tpu import obs

        params, cfg = tiny_model
        prompts = [_repetitive_prompt(40), _repetitive_prompt(41)]
        _, ref, _ = _drain(
            params, cfg, prompts, [16, 16], speculative=False,
        )
        obs.configure(enabled=True, events_out=str(tmp_path / "ev.jsonl"))
        obs.reset_stats()
        # after=4 skips the admission-phase scheduler_chunk hits so the
        # fault lands on a speculative dispatch with both rows resident.
        inj = self._arm("bug@scheduler_chunk:after=4:times=1:slot=0")
        try:
            b, out, results = _drain(
                params, cfg, prompts, [16, 16],
                speculative=True, gamma=4, page_size=4,
                prefix_cache=False,
            )
        finally:
            inj.reset()
        by_id = {r.req_id: r for r in results}
        assert by_id[0].error is not None
        assert by_id[0].fault_kind is not None
        assert by_id[1].error is None
        assert out[1] == ref[1], "co-resident tokens perturbed"
        b.allocator.check_invariants()
        assert b.allocator.free_pages == b.allocator.n_pages
        # The flight recorder dumped at the moment of eviction.
        dump = tmp_path / "ev.fault.jsonl"
        assert dump.exists()
        events = [json.loads(ln) for ln in dump.read_text().splitlines()]
        faults = [e for e in events if e["type"] == "fault"]
        assert faults, "no FaultEvent in the auto-dump"
        last = faults[-1]
        assert last["slot"] == 0
        assert last["pages_freed"] > 0
        assert last["kind"]
        assert any(e["type"] == "spec" for e in events), (
            "SpecEvents missing from the reconstruction"
        )

    def test_kv_alloc_fault_during_spec_prepare_contained(self, tiny_model):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(40), _repetitive_prompt(41)]
        _, ref, _ = _drain(
            params, cfg, prompts, [16, 16], speculative=False,
        )
        # Skip the admission-time kv_alloc hits; fire on the per-step
        # coverage extension inside _prepare_spec_step.
        inj = self._arm("bug@kv_alloc:after=2:times=1:slot=0")
        try:
            b, out, results = _drain(
                params, cfg, prompts, [16, 16],
                speculative=True, gamma=4, page_size=4,
                prefix_cache=False,
            )
        finally:
            inj.reset()
        by_id = {r.req_id: r for r in results}
        assert by_id[0].error is not None
        assert by_id[1].error is None
        assert out[1] == ref[1]
        b.allocator.check_invariants()
        assert b.allocator.free_pages == b.allocator.n_pages

    def test_chaos_fuzz_no_request_lost_with_spec(self, tiny_model):
        """The resilience fuzz invariant, speculation enabled: every
        req_id resolves exactly once, pool invariants hold, and all
        pages return — under random kv_alloc/scheduler_chunk faults."""
        from adversarial_spec_tpu.resilience import injector as inj_mod
        from adversarial_spec_tpu.resilience.faults import FaultKind
        from adversarial_spec_tpu.resilience.injector import (
            FaultInjector,
            FaultRule,
        )

        params, cfg = tiny_model
        kinds = list(FaultKind)
        seams = ["scheduler_chunk", "kv_alloc"]
        for seed in (0, 1, 2):
            rng = random.Random(seed)
            rules = [
                FaultRule(
                    kind=rng.choice(kinds),
                    seam=rng.choice(seams),
                    p=0.25,
                    slot=rng.choice([None, 0, 1]),
                )
                for _ in range(rng.randrange(1, 3))
            ]
            inj_mod.install(FaultInjector(rules, seed=seed))
            try:
                n_req = rng.randrange(3, 6)
                prompts = [
                    _repetitive_prompt(10 + (i * 13) % 40)
                    for i in range(n_req)
                ]
                budgets = [4 + (i * 3) % 12 for i in range(n_req)]
                b, _, results = _drain(
                    params, cfg, prompts, budgets, max_batch=2,
                    speculative=True, gamma=3, page_size=4,
                    prefix_cache=False, timeout_s=60.0,
                )
            finally:
                inj_mod.reset()
            assert sorted(r.req_id for r in results) == list(range(n_req))
            b.allocator.check_invariants()
            assert b.allocator.free_pages == b.allocator.n_pages


class TestSlotReuseWithSpec:
    def test_multi_token_steps_respect_generation_guard(self, tiny_model):
        """The multi-token analog of the slot-reuse regression: steps
        emitting 1..γ+1 tokens per row, slots churning through mixed
        budgets — a freed-and-readmitted slot must not inherit the old
        owner's counts or flags. Every request must equal its solo
        dense reference."""
        params, cfg = tiny_model
        prompts = [
            _repetitive_prompt(
                120 if i % 2 == 0 else 17, period=5 + i % 3
            )
            for i in range(6)
        ]
        budgets = [8 if i % 2 == 0 else 16 for i in range(6)]
        _, out, results = _drain(
            params, cfg, prompts, budgets, max_batch=2, chunk=8,
            speculative=True, gamma=4, interleave=True,
        )
        assert [r.req_id for r in results] == list(range(6))
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            ref = generate(
                params, cfg, [p], max_new_tokens=n, eos_ids=[],
                greedy=True, speculative=False,
            )
            np.testing.assert_array_equal(
                out[i], ref.tokens[0, : ref.n_generated[0]],
                err_msg=f"req {i} (slot churn corrupted a row)",
            )


class TestGenerateSeamWarning:
    def test_paged_speculative_warns_once(self, tiny_model, capsys):
        """satellite: ``speculative and not paged`` used to silently
        disable speculation for paged generate() calls — now the flag
        interaction is named ONCE on stderr, and tokens are unchanged."""
        import adversarial_spec_tpu.engine.generate as gen_mod

        params, cfg = tiny_model
        prompt = _repetitive_prompt(24)
        kw = dict(
            max_new_tokens=16, eos_ids=[], greedy=True,
            paged=True, page_size=16, share_prefix=False,
        )
        gen_mod._PAGED_SPEC_WARNED = False
        try:
            out = generate(params, cfg, [prompt], speculative=True, **kw)
            err = capsys.readouterr().err
            assert "speculative=True is ignored when paged=True" in err
            assert "ContinuousBatcher" in err
            generate(params, cfg, [prompt], speculative=True, **kw)
            assert (
                "speculative=True is ignored"
                not in capsys.readouterr().err
            ), "warning must fire once per process"
        finally:
            gen_mod._PAGED_SPEC_WARNED = False
        ref = generate(params, cfg, [prompt], speculative=False, **kw)
        np.testing.assert_array_equal(out.tokens, ref.tokens)

    def test_paged_inherited_default_does_not_warn(
        self, tiny_model, capsys
    ):
        """Review regression: a paged generate() that merely INHERITED
        the default-on process config (the engine's dense fallback
        passes speculative=None) asked for nothing — warning it to
        'pass speculative=False' would be spurious noise once per
        process."""
        import adversarial_spec_tpu.engine.generate as gen_mod

        params, cfg = tiny_model
        gen_mod._PAGED_SPEC_WARNED = False
        spec_mod.configure(enabled=True)
        generate(
            params, cfg, [_repetitive_prompt(24)], max_new_tokens=16,
            eos_ids=[], greedy=True, paged=True, page_size=16,
            share_prefix=False,
        )
        assert "speculative=True is ignored" not in capsys.readouterr().err
        assert gen_mod._PAGED_SPEC_WARNED is False

    def test_dense_speculative_does_not_warn(self, tiny_model, capsys):
        import adversarial_spec_tpu.engine.generate as gen_mod

        params, cfg = tiny_model
        gen_mod._PAGED_SPEC_WARNED = False
        generate(
            params, cfg, [_repetitive_prompt(24)], max_new_tokens=16,
            eos_ids=[], greedy=True, speculative=True,
        )
        assert "speculative=True is ignored" not in capsys.readouterr().err


class TestCliSpecFlags:
    SPEC = "# Title\n" + "The allocator SHALL bound reuse. " * 30

    def _run(self, argv, monkeypatch, capsys):
        from adversarial_spec_tpu import cli

        monkeypatch.setattr("sys.stdin", io.StringIO(self.SPEC))
        code = cli.main(argv)
        out, err = capsys.readouterr()
        return code, json.loads(out), err

    def test_json_carries_spec_section_with_acceptance(
        self, monkeypatch, capsys
    ):
        """A mock critique round: the [SPEC] revision is a near-copy of
        the document, so the deterministic acceptance model records
        real accepts and ``perf.spec`` reports them."""
        code, data, _ = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["spec"]
        assert snap["enabled"] is True
        assert snap["gamma"] == spec_mod.DEFAULT_GAMMA
        assert snap["spec_steps"] > 0
        assert snap["acceptance_rate"] > 0
        assert snap["tokens_per_step"] > 1.0
        assert snap["emitted_tokens"] >= snap["accepted_tokens"]

    def test_no_speculative_escape_hatch(self, monkeypatch, capsys):
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://critic", "--json",
                "--no-speculative",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["spec"]
        assert snap["enabled"] is False
        assert snap["spec_steps"] == 0

    def test_gamma_flag_reaches_config(self, monkeypatch, capsys):
        code, data, _ = self._run(
            [
                "critique", "--models", "mock://critic", "--json",
                "--gamma", "4",
            ],
            monkeypatch, capsys,
        )
        assert code == 0
        assert data["perf"]["spec"]["gamma"] == 4

    def test_flags_do_not_leak_across_invocations(
        self, monkeypatch, capsys
    ):
        """One round's --no-speculative/--gamma must not leak into the
        next (flag-else-env-default per invocation, like obs)."""
        self._run(
            [
                "critique", "--models", "mock://critic", "--json",
                "--no-speculative", "--gamma", "2",
            ],
            monkeypatch, capsys,
        )
        code, data, _ = self._run(
            ["critique", "--models", "mock://critic", "--json"],
            monkeypatch, capsys,
        )
        assert code == 0
        snap = data["perf"]["spec"]
        assert snap["enabled"] is True
        assert snap["gamma"] == spec_mod.DEFAULT_GAMMA


class TestMockAcceptanceModel:
    def _chat(self, doc, rnd=1, n=1):
        from adversarial_spec_tpu.engine.mock import MockEngine
        from adversarial_spec_tpu.engine.types import (
            ChatRequest,
            SamplingParams,
        )

        eng = MockEngine()
        reqs = [
            ChatRequest(
                model="mock://critic",
                system="You are a critic.",
                user=(
                    f"Debate round {rnd}\n--- DOCUMENT ---\n{doc}"
                    "\n--- END DOCUMENT ---"
                ),
            )
            for _ in range(n)
        ]
        return eng.chat(reqs, SamplingParams())

    def test_deterministic_and_high_on_near_copy(self):
        doc = "All pages SHALL be refcounted and bounded. " * 30
        spec_mod.configure(enabled=True, gamma=8)
        spec_mod.reset_stats()
        self._chat(doc)
        s1 = spec_mod.stats.snapshot()
        assert s1["acceptance_rate"] > 0.3, "near-copy must accept"
        assert s1["tokens_per_step"] >= 2.0
        spec_mod.reset_stats()
        self._chat(doc)
        assert spec_mod.stats.snapshot() == s1  # byte-deterministic

    def test_replies_independent_of_spec_config(self):
        doc = "All pages SHALL be refcounted. " * 20
        on = [c.text for c in self._chat(doc)]
        spec_mod.configure(enabled=False)
        off = [c.text for c in self._chat(doc)]
        assert on == off

    def test_disabled_records_nothing(self):
        spec_mod.configure(enabled=False)
        spec_mod.reset_stats()
        self._chat("Words repeat here. " * 20)
        assert spec_mod.stats.spec_steps == 0


class TestBatcherSpecPallasVerify:
    """The γ-span verify routed through the multi-position paged Pallas
    kernel (``paged_decode_attention_mq``, interpret on CPU) must not
    change a single greedy token vs the XLA gather verify or plain
    dense decode — three arms, every draft width."""

    def _drain_kernel(self, params, cfg, prompts, budgets, *, eos=(), **kw):
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=kw.pop("max_batch", 2),
            max_new_cap=max(budgets),
            eos_ids=list(eos),
            **kw,
        )
        # Route attention through the Pallas kernels in interpret mode
        # (the batcher auto-enables them on TPU only).
        b._use_pallas = True
        b._pallas_interpret = True
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            b.submit(
                SchedRequest(req_id=i, prompt_ids=list(p), max_new_tokens=n)
            )
        results = b.run_all()
        return {r.req_id: r.tokens.tolist() for r in results}

    # Interpret-mode drains are wall-heavy, so the budgets stay small —
    # 8 tokens still crosses several verify spans at every γ here.
    @pytest.mark.parametrize("gamma", [2, 4, 8])
    def test_three_arm_parity(self, tiny_model, gamma):
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(44), _repetitive_prompt(52, period=5)]
        budgets = [8, 8]
        _, xla, _ = _drain(
            params, cfg, prompts, budgets, speculative=True, gamma=gamma
        )
        kern = self._drain_kernel(
            params, cfg, prompts, budgets, speculative=True, gamma=gamma
        )
        assert xla == kern, f"gamma={gamma}: kernel verify changed tokens"
        for i, p in enumerate(prompts):
            ref = generate(
                params, cfg, [p], max_new_tokens=budgets[i], eos_ids=[],
                greedy=True, speculative=False,
            )
            np.testing.assert_array_equal(
                kern[i], ref.tokens[0, : ref.n_generated[0]],
                err_msg=f"gamma={gamma} req {i} vs dense reference",
            )

    def test_eos_inside_span_kernel_verify(self, tiny_model):
        """An EOS accepted mid-span through the kernel verify must stop
        the row exactly where the XLA verify (and plain decode) stops."""
        params, cfg = tiny_model
        prompts = [_repetitive_prompt(40)]
        _, probe, _ = _drain(
            params, cfg, prompts, [16], max_batch=1, speculative=False,
        )
        out = probe[0]
        if len(out) < 4:
            pytest.skip("probe output too short to pick a mid-run EOS")
        eos = out[len(out) // 2]
        _, off, _ = _drain(
            params, cfg, prompts, [16], max_batch=1, eos=[eos],
            speculative=False,
        )
        kern = self._drain_kernel(
            params, cfg, prompts, [16], max_batch=1, eos=[eos],
            speculative=True, gamma=4,
        )
        assert kern == off
        assert kern[0][-1] == eos  # EOS kept, nothing after
