"""Prompt-lookup speculative decoding tests.

Correctness contract: speculative greedy decode is BIT-IDENTICAL to plain
greedy decode (acceptance only reorders how many tokens emerge per
forward, never which tokens)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import speculative as spec_mod
from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


class TestSpeculativeParity:
    def test_matches_plain_greedy(self, tiny_model):
        params, cfg = tiny_model
        prompt = [((i * 13) % 500) + 3 for i in range(40)]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, [prompt], speculative=False, **kw)
        spec = generate(params, cfg, [prompt], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_matches_with_repetitive_prompt(self, tiny_model):
        """Repetitive prompts maximize n-gram matches (acceptance both
        succeeds and fails along the way) — parity must still hold."""
        params, cfg = tiny_model
        prompt = [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9]
        kw = dict(max_new_tokens=20, eos_ids=[], greedy=True)
        plain = generate(params, cfg, [prompt], speculative=False, **kw)
        spec = generate(params, cfg, [prompt], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)

    def test_eos_parity(self, tiny_model):
        params, cfg = tiny_model
        probe = generate(
            params, cfg, [[1, 2]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 1])
        kw = dict(max_new_tokens=30, eos_ids=[eos], greedy=True)
        plain = generate(params, cfg, [[1, 2]], speculative=False, **kw)
        spec = generate(params, cfg, [[1, 2]], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_disabled_for_batches_and_sampling(self, tiny_model):
        """Multi-row and temperature>0 silently use the plain path (no
        crash, valid output shapes)."""
        params, cfg = tiny_model
        multi = generate(
            params,
            cfg,
            [[1, 2], [3, 4]],
            max_new_tokens=6,
            eos_ids=[],
            greedy=True,
            speculative=True,
        )
        assert multi.tokens.shape == (2, 6)
        sampled = generate(
            params,
            cfg,
            [[1, 2]],
            max_new_tokens=6,
            eos_ids=[],
            temperature=1.0,
            seed=3,
            speculative=True,
        )
        assert sampled.tokens.shape == (1, 6)


class TestAcceptanceArithmetic:
    def test_full_acceptance_advances_gamma_plus_one(self, monkeypatch):
        """With a forward whose greedy chain always equals the draft, each
        speculative step must emit γ+1 tokens (all drafts + bonus)."""
        cfg = get_config("llama", "tiny")
        V = cfg.vocab_size

        def fake_forward(params, cfg_, toks, positions, cache, ci, kv, **kw):
            # argmax(logits[i]) == toks[i+1] for i < span-1 (accept all);
            # last position predicts token 7 (the bonus).
            span = toks.shape[1]
            nxt = jnp.concatenate(
                [toks[0, 1:], jnp.array([7], toks.dtype)]
            )
            logits = jax.nn.one_hot(nxt, V, dtype=jnp.float32)[None] * 10.0
            return logits, cache

        monkeypatch.setattr(spec_mod, "forward", fake_forward)

        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        S, max_new, gamma = 16, 32, spec_mod.GAMMA
        prompt = jnp.arange(3, 3 + S, dtype=jnp.int32)[None]
        cache = T.init_cache(cfg, 1, S + max_new, dtype=jnp.float32)
        out_buf = jnp.zeros((1, max_new), jnp.int32)

        cache, prev, cur, finished, out_buf, step, n_iters = (
            spec_mod.speculative_decode_steps(
                params,
                cfg,
                cache,
                prompt,
                prompt[0, -2],
                prompt[0, -1],
                jnp.zeros((1,), jnp.int32),
                jnp.zeros((1,), bool),
                out_buf,
                jnp.int32(1),
                jnp.int32(max_new),
                jnp.asarray([-1], jnp.int32),
                prompt_len=S,
                chunk=64,
            )
        )
        # [prev, cur] = last two prompt tokens match at the prompt's end;
        # clamped draft comes from the prompt tail and fully verifies, so
        # every iteration advances by γ+1.
        n_steps = int(step) - 1
        assert n_steps % (gamma + 1) == 0
        assert n_steps >= gamma + 1
        # Every verification forward emitted the full span.
        assert n_steps == int(n_iters) * (gamma + 1)

    def test_zero_acceptance_advances_one(self, monkeypatch):
        """A forward that contradicts every draft must still emit exactly
        one (correct) token per step — guaranteed progress."""
        cfg = get_config("llama", "tiny")
        V = cfg.vocab_size

        def fake_forward(params, cfg_, toks, positions, cache, ci, kv, **kw):
            span = toks.shape[1]
            # Predict token (draft + 1) everywhere: never matches drafts.
            nxt = (toks[0] + 1) % V
            logits = jax.nn.one_hot(nxt, V, dtype=jnp.float32)[None] * 10.0
            return logits, cache

        monkeypatch.setattr(spec_mod, "forward", fake_forward)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        S, max_new = 16, 16
        prompt = jnp.arange(3, 3 + S, dtype=jnp.int32)[None]
        cache = T.init_cache(cfg, 1, S + max_new, dtype=jnp.float32)
        out_buf = jnp.zeros((1, max_new), jnp.int32)
        _, _, _, _, out_buf, step, n_iters = spec_mod.speculative_decode_steps(
            params,
            cfg,
            cache,
            prompt,
            prompt[0, -2],
            prompt[0, -1],
            jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), bool),
            out_buf,
            jnp.int32(1),
            jnp.int32(max_new),
            jnp.asarray([-1], jnp.int32),
            prompt_len=S,
            chunk=3,  # 3 single-token steps fit the chunk bound
        )
        assert int(step) == 4  # start 1 + chunk bound 3 → exactly 3 steps
        assert int(n_iters) == 3  # one wide forward per single emitted token
