"""Prompt-lookup speculative decoding tests.

Correctness contracts:
- greedy speculative decode is BIT-IDENTICAL to plain greedy decode, at
  any batch size (acceptance only reorders how many tokens emerge per
  forward, never which tokens);
- at temperature > 0, rejection sampling preserves the sampling
  distribution exactly (tested at the per-step marginal).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.engine import speculative as spec_mod
from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config


def test_gamma_env_validated_at_import():
    """ADVSPEC_GAMMA=0 must fail at the knob with an actionable message,
    not deep inside a traced accept loop."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    env = dict(os.environ)
    env.update(
        ADVSPEC_GAMMA="0",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(Path(__file__).resolve().parent.parent),
    )
    proc = subprocess.run(
        [sys.executable, "-c",
         "import adversarial_spec_tpu.engine.speculative"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0
    assert "ADVSPEC_GAMMA must be >= 1" in proc.stderr


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    return params, cfg


def _spec_args(prompt, max_new, *, B=1, key_seed=0):
    """Boilerplate state for direct speculative_decode_steps calls."""
    S = prompt.shape[1]
    cfg = get_config("llama", "tiny")
    cache = T.init_cache(cfg, B, S + max_new, dtype=jnp.float32)
    out_buf = jnp.zeros((B, max_new), jnp.int32)
    return dict(
        cache=cache,
        prompt_tokens=prompt,
        prev_tokens=jnp.broadcast_to(prompt[0, -2], (B,)),
        cur_tokens=jnp.broadcast_to(prompt[0, -1], (B,)),
        pad_lens=jnp.zeros((B,), jnp.int32),
        finished=jnp.zeros((B,), bool),
        out_buf=out_buf,
        steps=jnp.ones((B,), jnp.int32),
        stop_at=jnp.int32(max_new),
        eos_ids=jnp.asarray([-1], jnp.int32),
        key=jax.random.key(key_seed),
        temperature=jnp.float32(0.0),
        top_p=jnp.float32(1.0),
    )


class TestSpeculativeParity:
    def test_matches_plain_greedy(self, tiny_model):
        params, cfg = tiny_model
        prompt = [((i * 13) % 500) + 3 for i in range(40)]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, [prompt], speculative=False, **kw)
        spec = generate(params, cfg, [prompt], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_matches_plain_greedy_batched(self, tiny_model):
        """The round-2 headline: B>1 rows accept different draft counts,
        desynchronize, and must still reproduce plain greedy exactly
        (spec phase + rowwise tail both covered)."""
        params, cfg = tiny_model
        prompts = [
            [((i * 13) % 500) + 3 for i in range(40)],
            [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9],
            [((i * 7) % 450) + 9 for i in range(25)],
        ]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        spec = generate(params, cfg, prompts, speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_matches_with_repetitive_prompt(self, tiny_model):
        """Repetitive prompts maximize n-gram matches (acceptance both
        succeeds and fails along the way) — parity must still hold."""
        params, cfg = tiny_model
        prompt = [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9]
        kw = dict(max_new_tokens=20, eos_ids=[], greedy=True)
        plain = generate(params, cfg, [prompt], speculative=False, **kw)
        spec = generate(params, cfg, [prompt], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)

    def test_eos_parity(self, tiny_model):
        params, cfg = tiny_model
        probe = generate(
            params, cfg, [[1, 2]], max_new_tokens=4, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 1])
        kw = dict(max_new_tokens=30, eos_ids=[eos], greedy=True)
        plain = generate(params, cfg, [[1, 2]], speculative=False, **kw)
        spec = generate(params, cfg, [[1, 2]], speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_eos_parity_batched(self, tiny_model):
        """Rows hitting EOS at different steps freeze while others keep
        speculating; outputs must match plain greedy row-for-row."""
        params, cfg = tiny_model
        prompts = [[1, 2], [7, 3, 9], [2, 2, 2, 2]]
        probe = generate(
            params, cfg, prompts, max_new_tokens=6, eos_ids=[], greedy=True
        )
        eos = int(probe.tokens[0, 2])
        kw = dict(max_new_tokens=30, eos_ids=[eos], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        spec = generate(params, cfg, prompts, speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)

    def test_sampled_batch_shapes_and_validity(self, tiny_model):
        """Temperature speculation: shapes, vocab range, and n_generated
        bookkeeping hold for the bench shape (4 rows, temp 0.7)."""
        params, cfg = tiny_model
        prompts = [[3 + i, 40 + i, 3 + i, 40 + i] * 4 for i in range(4)]
        out = generate(
            params,
            cfg,
            prompts,
            max_new_tokens=16,
            eos_ids=[],
            temperature=0.7,
            seed=11,
            speculative=True,
        )
        assert out.tokens.shape == (4, 16)
        assert (out.tokens >= 0).all() and (out.tokens < cfg.vocab_size).all()
        np.testing.assert_array_equal(out.n_generated, [16] * 4)


class TestRejectionSamplingMarginal:
    def test_first_token_marginal_matches_target(self, monkeypatch):
        """The step marginal must equal the target distribution p exactly:
        P(tok = d) = p(d) via acceptance, P(tok = x≠d) = (1-p(d)) ·
        p(x)/(1-p(d)) via the residual. Monte Carlo over seeds against a
        forward with a known 4-token distribution."""
        cfg = get_config("llama", "tiny")
        V = cfg.vocab_size
        support = np.array([10, 20, 30, 40])
        target = np.array([0.4, 0.3, 0.2, 0.1])
        base = np.full((V,), -1e9, np.float32)
        base[support] = np.log(target)

        def fake_forward(params, cfg_, toks, positions, cache, ci, kv, **kw):
            B, span = toks.shape
            logits = jnp.broadcast_to(
                jnp.asarray(base)[None, None, :], (B, span, V)
            )
            return logits, cache

        monkeypatch.setattr(spec_mod, "forward", fake_forward)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        # Prompt engineered so the [prev, cur] bigram matches mid-prompt
        # and the draft is token 10 (the high-probability one): both the
        # accept and the reject→residual paths get exercised.
        prompt = jnp.asarray(
            [[7, 8, 10, 10, 10, 10, 10, 10, 10, 10, 10, 7, 8]], jnp.int32
        )
        counts = {int(t): 0 for t in support}
        N = 400
        for seed in range(N):
            args = _spec_args(prompt, max_new=16, key_seed=seed)
            args["temperature"] = jnp.float32(1.0)
            out = spec_mod.speculative_decode_steps(
                params,
                cfg,
                **args,
                prompt_len=prompt.shape[1],
                iters=1,
                greedy=False,
            )
            first = int(np.asarray(out[4])[0, 1])  # out_buf slot 1
            assert first in counts, f"emitted off-support token {first}"
            counts[first] += 1
        freq = np.array([counts[int(t)] for t in support]) / N
        np.testing.assert_allclose(freq, target, atol=0.07)


class TestAcceptanceArithmetic:
    def test_full_acceptance_advances_gamma_plus_one(self, monkeypatch):
        """With a forward whose greedy chain always equals the draft, each
        speculative step must emit γ+1 tokens (all drafts + bonus)."""
        cfg = get_config("llama", "tiny")
        V = cfg.vocab_size

        def fake_forward(params, cfg_, toks, positions, cache, ci, kv, **kw):
            # argmax(logits[i]) == toks[i+1] for i < span-1 (accept all);
            # last position predicts token 7 (the bonus).
            nxt = jnp.concatenate(
                [toks[0, 1:], jnp.array([7], toks.dtype)]
            )
            logits = jax.nn.one_hot(nxt, V, dtype=jnp.float32)[None] * 10.0
            return logits, cache

        monkeypatch.setattr(spec_mod, "forward", fake_forward)

        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        S, max_new, gamma = 16, 32, spec_mod.GAMMA
        prompt = jnp.arange(3, 3 + S, dtype=jnp.int32)[None]
        args = _spec_args(prompt, max_new)
        out = spec_mod.speculative_decode_steps(
            params,
            cfg,
            **args,
            prompt_len=S,
            iters=8,
            greedy=True,
        )
        steps, n_iters = out[5], out[6]
        n_steps = int(steps[0]) - 1
        assert n_steps % (gamma + 1) == 0
        assert n_steps >= gamma + 1
        # Every verification forward emitted the full span.
        assert n_steps == int(n_iters) * (gamma + 1)

    def test_zero_acceptance_advances_one(self, monkeypatch):
        """A forward that contradicts every draft must still emit exactly
        one (correct) token per step — guaranteed progress."""
        cfg = get_config("llama", "tiny")
        V = cfg.vocab_size

        def fake_forward(params, cfg_, toks, positions, cache, ci, kv, **kw):
            # Predict token (draft + 1) everywhere: never matches drafts.
            nxt = (toks[0] + 1) % V
            logits = jax.nn.one_hot(nxt, V, dtype=jnp.float32)[None] * 10.0
            return logits, cache

        monkeypatch.setattr(spec_mod, "forward", fake_forward)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        S, max_new = 16, 16
        prompt = jnp.arange(3, 3 + S, dtype=jnp.int32)[None]
        args = _spec_args(prompt, max_new)
        out = spec_mod.speculative_decode_steps(
            params,
            cfg,
            **args,
            prompt_len=S,
            iters=3,
        )
        steps, n_iters = out[5], out[6]
        assert int(steps[0]) == 4  # start 1 + 3 iterations × 1 token
        assert int(n_iters) == 3  # one wide forward per emitted token


class TestAdaptiveResync:
    def test_off_switch_resyncs_then_matches_plain_greedy(self, tiny_model):
        """Random prompts make prompt-lookup drafts useless: the adaptive
        off-switch fires, laggards catch up on the rowwise loop, and the
        remaining budget (crossing a chunk boundary) decodes on the
        shared-slot path — output must stay bit-identical to plain
        greedy throughout the mode changes."""
        from adversarial_spec_tpu.engine.generate import DECODE_CHUNK

        params, cfg = tiny_model
        rng = np.random.default_rng(3)
        prompts = [
            list(rng.integers(3, 500, 31)),
            list(rng.integers(3, 500, 17)),
            list(rng.integers(3, 500, 40)),
        ]
        kw = dict(max_new_tokens=DECODE_CHUNK + 12, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        spec = generate(params, cfg, prompts, speculative=True, **kw)
        np.testing.assert_array_equal(plain.tokens, spec.tokens)
        np.testing.assert_array_equal(plain.n_generated, spec.n_generated)


class TestSpeculativeUnderDp:
    def test_dp_spec_matches_single_device_greedy(self, tiny_model):
        """Greedy speculation with rows dp-sharded (each device runs its
        own accept loop; telemetry psums) must be bit-identical to the
        single-device speculative run AND to plain greedy decode."""
        import jax as _jax

        if len(_jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [
            [((i * 13) % 500) + 3 for i in range(40)],
            [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9],
            [((i * 7) % 450) + 9 for i in range(25)],
            [9, 1, 9, 1, 9, 1, 9, 1, 9, 1],
        ]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        single = generate(params, cfg, prompts, speculative=True,
                          share_prefix=False, **kw)
        mesh = make_mesh({"dp": 4})
        sharded = shard_params(mesh, params)
        with mesh:
            dp = generate(
                sharded, cfg, prompts, speculative=True, mesh=mesh, **kw
            )
        np.testing.assert_array_equal(plain.tokens, single.tokens)
        np.testing.assert_array_equal(plain.tokens, dp.tokens)
        np.testing.assert_array_equal(plain.n_generated, dp.n_generated)

    def test_tp_spec_matches_single_device_greedy(self, tiny_model):
        """Greedy speculation on a tp-only mesh (one GSPMD-partitioned
        program: Megatron-sharded matmuls, compiler-inserted psums) must
        be bit-identical to plain greedy decode — BASELINE config 5's
        70B-judge-under-TP decode lever."""
        import jax as _jax

        if len(_jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [
            [((i * 13) % 500) + 3 for i in range(40)],
            [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9],
        ]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        mesh = make_mesh({"dp": 1, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            tp = generate(
                sharded, cfg, prompts, speculative=True, mesh=mesh, **kw
            )
        np.testing.assert_array_equal(plain.tokens, tp.tokens)
        np.testing.assert_array_equal(plain.n_generated, tp.n_generated)

    def test_dp_tp_spec_matches_single_device_greedy(self, tiny_model):
        """Greedy speculation on a MIXED dp=2 × tp=2 mesh (rows GSPMD-
        sharded over dp, matmuls over tp, one lockstep program)."""
        import jax as _jax

        if len(_jax.devices()) < 4:
            pytest.skip("requires 4 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [
            [((i * 13) % 500) + 3 for i in range(40)],
            [5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9, 7, 5, 9],
            [((i * 7) % 450) + 9 for i in range(25)],
            [9, 1, 9, 1, 9, 1, 9, 1, 9, 1],
        ]
        kw = dict(max_new_tokens=24, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        mesh = make_mesh({"dp": 2, "tp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            mixed = generate(
                sharded, cfg, prompts, speculative=True, mesh=mesh, **kw
            )
        np.testing.assert_array_equal(plain.tokens, mixed.tokens)
        np.testing.assert_array_equal(plain.n_generated, mixed.n_generated)

    def test_dp_spec_row_padding(self, tiny_model):
        """3 rows on dp=2: generate pads to 4, drops the pad row, and the
        dp speculative path must not disturb real rows' outputs."""
        import jax as _jax

        if len(_jax.devices()) < 2:
            pytest.skip("requires 2 virtual devices")
        from adversarial_spec_tpu.parallel.mesh import make_mesh
        from adversarial_spec_tpu.parallel.sharding import shard_params

        params, cfg = tiny_model
        prompts = [
            [5, 9, 7, 5, 9, 7, 5, 9],
            [((i * 11) % 400) + 7 for i in range(19)],
            [3, 3, 3, 3, 3, 3],
        ]
        kw = dict(max_new_tokens=20, eos_ids=[], greedy=True)
        plain = generate(params, cfg, prompts, speculative=False, **kw)
        mesh = make_mesh({"dp": 2})
        sharded = shard_params(mesh, params)
        with mesh:
            dp = generate(
                sharded, cfg, prompts, speculative=True, mesh=mesh, **kw
            )
        np.testing.assert_array_equal(plain.tokens, dp.tokens)
