"""Streaming token API + early-convergence cancellation.

Covers the whole stack: the incremental marker scanner
(debate/parsing.StreamScanner), the mock engine's deterministic chunked
delivery, the ContinuousBatcher's mid-decode cancellation (byte parity
up to the cancel point, page/slot surgery, partial-prefix salvage,
spec-path composition), the debate core's consumer wiring, CLI flag
plumbing, and the obs/tooling render path (CancelEvent schema,
``cancelled`` span phase, trace_view decomposition).
"""

from __future__ import annotations

import json
import random

import numpy as np
import pytest

from adversarial_spec_tpu import obs
from adversarial_spec_tpu.debate import parsing
from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.debate.parsing import (
    AGREE_MARKER,
    StreamScanner,
    detect_agreement,
    get_critique_summary,
)
from adversarial_spec_tpu.engine import streaming
from adversarial_spec_tpu.engine.mock import MockEngine
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams


@pytest.fixture(autouse=True)
def _spec_off():
    """Speculation off by default in this module (suite wall budget —
    the PR 6 precedent); the spec-composition tests opt back in
    explicitly."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=True)


# -- incremental marker scanner ------------------------------------------


class TestStreamScanner:
    def test_marker_split_across_two_chunks(self):
        sc = StreamScanner()
        assert sc.feed("critique [AGR") is None
        assert sc.feed("critique [AGREE] done") == AGREE_MARKER
        assert sc.found_at == 9

    def test_marker_split_across_three_chunks(self):
        sc = StreamScanner()
        assert sc.feed("[A") is None
        assert sc.feed("[AGRE") is None
        assert sc.feed("[AGREE]") == AGREE_MARKER
        assert sc.found_at == 0

    def test_marker_inside_code_fence_counts(self):
        # Substring semantics deliberately mirror detect_agreement
        # (bare substring, reference parity): a fenced marker counts
        # for BOTH parsers, so the incremental verdict can never
        # diverge from the whole-text one.
        text = "look:\n```\n[AGREE]\n```\nnot really"
        sc = StreamScanner()
        assert sc.feed(text) == AGREE_MARKER
        assert detect_agreement(text)

    def test_marker_never_arrives(self):
        sc = StreamScanner()
        text = "a long critique with no verdict marker at all" * 8
        for end in range(0, len(text) + 1, 7):
            assert sc.feed(text[:end]) is None
        assert sc.feed(text) is None  # EOS: falls through, no verdict

    def test_verdict_sticky(self):
        sc = StreamScanner()
        sc.feed("x [AGREE]")
        at = sc.found_at
        assert sc.feed("x [AGREE] more text [AGREE]") == AGREE_MARKER
        assert sc.found_at == at  # first find wins, no rescan

    def test_custom_marker_list_earliest_wins(self):
        sc = StreamScanner(markers=("[DONE]", AGREE_MARKER))
        assert sc.feed("a [AGREE] b [DONE]") == AGREE_MARKER

    def test_fuzz_matches_whole_text_parser(self):
        rng = random.Random(7)
        pieces = ["crit ", "[AG", "REE]", "[A", "GREE", "]", "x", "[AGREE]"]
        for trial in range(200):
            n = rng.randrange(1, 7)
            text = "".join(rng.choice(pieces) for _ in range(n))
            # Random chunking of the stream.
            sc = StreamScanner()
            verdict = None
            pos = 0
            while pos < len(text):
                pos = min(pos + rng.randrange(1, 9), len(text))
                verdict = sc.feed(text[:pos])
            whole = AGREE_MARKER in text
            assert (verdict == AGREE_MARKER) == whole, (trial, text)
            if whole:
                assert sc.found_at == text.find(AGREE_MARKER), text


class TestMarkerCleanup:
    def test_summary_strips_every_cancel_marker(self, monkeypatch):
        # Regression pin for the marker-list-driven cleanup: a section
        # marker added to EARLY_CANCEL_MARKERS is stripped from
        # summaries by the SAME path as [AGREE] — no second list.
        monkeypatch.setattr(
            parsing,
            "EARLY_CANCEL_MARKERS",
            (AGREE_MARKER, "[VERDICT]"),
        )
        s = get_critique_summary("[VERDICT] [AGREE] the spec is fine")
        assert "[VERDICT]" not in s and AGREE_MARKER not in s
        assert s == "the spec is fine"

    def test_summary_still_strips_agree(self):
        assert (
            get_critique_summary("[AGREE]\nall good") == "all good"
        )


# -- mock engine streaming ------------------------------------------------


def _agree_req(tail=50, model=None):
    return ChatRequest(
        model=model or f"mock://critic?agree_after=1&agree_tail={tail}",
        system="sys",
        user="Debate round 1\n--- DOCUMENT ---\nspec text\n--- END DOCUMENT ---",
    )


class TestMockStreaming:
    def test_cancel_truncates_to_blocking_prefix(self):
        full = MockEngine().chat([_agree_req()], SamplingParams())[0]
        sc = StreamScanner()

        def consumer(row, text):
            return sc.feed(text) is None

        out = MockEngine().chat(
            [_agree_req()], SamplingParams(), consumer=consumer
        )[0]
        assert out.cancelled
        assert full.text.startswith(out.text)  # byte-identical prefix
        assert detect_agreement(out.text)
        assert len(out.text) < len(full.text)

    def test_no_consumer_is_blocking_path(self):
        a = MockEngine().chat([_agree_req()], SamplingParams())[0]
        b = MockEngine().chat([_agree_req()], SamplingParams())[0]
        assert a.text == b.text and not a.cancelled

    def test_stream_disabled_ignores_consumer(self):
        streaming.configure(enabled=False)
        calls = []
        out = MockEngine().chat(
            [_agree_req()],
            SamplingParams(),
            consumer=lambda r, t: calls.append(t) or False,
        )[0]
        assert not out.cancelled and not calls

    def test_saved_tokens_accounted(self):
        streaming.reset_stats()
        sc = StreamScanner()
        MockEngine().chat(
            [_agree_req(tail=100)],
            SamplingParams(),
            consumer=lambda r, t: sc.feed(t) is None,
        )
        snap = streaming.snapshot()
        assert snap["cancels"] == 1
        assert snap["tokens_saved"] > 0
        assert 0.0 < snap["saved_fraction"] <= 1.0

    def test_raising_consumer_degrades_to_blocking(self):
        def bad(row, text):
            raise RuntimeError("boom")

        out = MockEngine().chat(
            [_agree_req()], SamplingParams(), consumer=bad
        )[0]
        full = MockEngine().chat([_agree_req()], SamplingParams())[0]
        assert out.text == full.text and not out.cancelled

    def test_cancel_emits_schema(self, tmp_path):
        obs.reset_stats()
        sc = StreamScanner()
        MockEngine().chat(
            [_agree_req()],
            SamplingParams(),
            consumer=lambda r, t: sc.feed(t) is None,
        )
        events = obs.recorder.events()
        cancels = [e for e in events if e["type"] == "cancel"]
        assert len(cancels) == 1
        assert cancels[0]["reason"] == "early_converge"
        assert cancels[0]["tokens_saved"] > 0
        for e in events:
            assert obs.validate_event(e) == [], e
        states = [
            e["state"] for e in events if e["type"] == "request"
        ]
        assert states[-1] == "cancelled"
        req_span = [
            e
            for e in events
            if e["type"] == "span" and e["name"] == "request"
        ]
        assert req_span[-1]["phase"] == "cancelled"
        snap = obs.metrics.snapshot()
        assert (
            snap['advspec_cancelled_total{reason="early_converge"}'] == 1
        )


# -- debate core wiring ---------------------------------------------------


class TestRoundIntegration:
    def test_round_cancels_agree_and_keeps_critics(self):
        streaming.reset_stats()
        r = run_round(
            "spec body",
            [
                "mock://critic?agree_after=1&agree_tail=80",
                "mock://critic",
            ],
            round_num=1,
        )
        agree, critic = r.responses
        assert agree.agreed and detect_agreement(agree.critique)
        assert not critic.agreed and "[SPEC]" in critic.critique
        assert streaming.stats.cancels == 1

    def test_early_cancel_off_streams_nothing(self):
        streaming.configure(early_cancel=False)
        streaming.reset_stats()
        r = run_round(
            "spec body",
            ["mock://critic?agree_after=1&agree_tail=80"],
            round_num=1,
        )
        assert streaming.stats.cancels == 0
        assert "remark 80" in r.responses[0].critique  # full tail decoded

    def test_two_arg_engine_fake_still_works(self):
        # An engine without the consumer seam (the pre-streaming
        # 2-argument chat) must serve the blocking path unmodified.
        class OldEngine:
            def chat(self, requests, params):
                from adversarial_spec_tpu.engine.types import Completion

                return [Completion(text="[AGREE] ok") for _ in requests]

            def validate(self, model):
                return None

        from adversarial_spec_tpu.engine import dispatch

        eng = OldEngine()
        assert not streaming.consumer_supported(eng)
        dispatch._ENGINE_CACHE["mock"] = eng
        r = run_round("spec", ["mock://whatever"], round_num=1)
        assert r.responses[0].critique == "[AGREE] ok"

    def test_round_transcripts_prefix_of_blocking(self):
        models = ["mock://critic?agree_after=1&agree_tail=40"]
        streaming.configure(enabled=False)
        blocking = run_round("spec", models, round_num=1)
        streaming.configure(enabled=True, early_cancel=True)
        streamed = run_round("spec", models, round_num=1)
        full = blocking.responses[0].critique
        part = streamed.responses[0].critique
        assert full.startswith(part) and len(part) < len(full)


# -- continuous batcher ---------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, jnp.float32)
    return params, cfg


def _mk_batcher(tiny_model, **kw):
    from adversarial_spec_tpu.engine.scheduler import ContinuousBatcher

    params, cfg = tiny_model
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_new_cap", 48)
    kw.setdefault("page_size", 64)
    kw.setdefault("capacity_tokens", 8192)
    kw.setdefault("greedy", True)
    return ContinuousBatcher(params, cfg, **kw)


def _drain(b, prompts, budget=48, cancel_after=None, cancel_rows=()):
    from adversarial_spec_tpu.engine.scheduler import SchedRequest

    delivered: dict[int, list[int]] = {}
    for i, p in enumerate(prompts):
        cb = None
        if i in cancel_rows:
            def cb(toks, _i=i):
                delivered[_i] = [int(t) for t in toks]
                return not (
                    cancel_after is not None and len(toks) >= cancel_after
                )
        b.submit(
            SchedRequest(
                req_id=i, prompt_ids=p, max_new_tokens=budget, on_tokens=cb
            )
        )
    res = b.run_all()
    b.allocator.check_invariants()
    return res, delivered


PROMPTS = [[5, 6, 7, 8] * 20, [9, 10, 11, 12] * 20]


class TestBatcherCancel:
    @pytest.mark.parametrize(
        "kw",
        [
            {},  # pipelined, prefix cache on
            {"interleave": False},  # legacy loop
            {"prefix_cache": False},  # padded layout
            {"pipeline_depth": 1},
        ],
        ids=["pipelined", "legacy", "no-prefix-cache", "depth1"],
    )
    def test_cancel_prefix_parity_and_readmission(self, tiny_model, kw):
        ref, _ = _drain(_mk_batcher(tiny_model, **kw), PROMPTS)
        res, delivered = _drain(
            _mk_batcher(tiny_model, **kw),
            PROMPTS,
            cancel_after=8,
            cancel_rows={0},
        )
        r0 = next(r for r in res if r.req_id == 0)
        r1 = next(r for r in res if r.req_id == 1)
        ref0 = next(r for r in ref if r.req_id == 0)
        assert r0.cancelled and r0.error is None
        # Byte-identical up to the cancellation point (greedy).
        assert (
            r0.tokens.tolist()
            == ref0.tokens.tolist()[: r0.n_generated]
        )
        assert r0.n_generated >= 8
        assert r0.tokens_saved == 48 - r0.n_generated
        # The consumer saw exactly the transcript prefix.
        assert delivered[0] == r0.tokens.tolist()
        # Co-resident unaffected.
        assert not r1.cancelled and r1.n_generated == 48

    def test_cancel_with_speculation_mid_span(self, tiny_model):
        # Mid-spec-span cancel: the per-step counts fetch rolled draft
        # pages back (PageAllocator.truncate) before the cancel runs;
        # invariants must hold after every cancel.
        from adversarial_spec_tpu.engine import spec as spec_mod

        spec_mod.configure(enabled=True, gamma=4)
        try:
            b = _mk_batcher(tiny_model, speculative=True, gamma=4)
            res, _ = _drain(b, PROMPTS, cancel_after=6, cancel_rows={0})
            r0 = next(r for r in res if r.req_id == 0)
            assert r0.cancelled and r0.spec_steps > 0
            ref, _ = _drain(
                _mk_batcher(tiny_model, speculative=True, gamma=4), PROMPTS
            )
            ref0 = next(r for r in ref if r.req_id == 0)
            assert (
                r0.tokens.tolist()
                == ref0.tokens.tolist()[: r0.n_generated]
            )
        finally:
            spec_mod.configure(enabled=False)

    def test_freed_slot_readmits_queued_request(self, tiny_model):
        # max_batch=1: the queued request can only start once the
        # cancelled one releases the slot — and it must start well
        # before the cancelled request's old budget would have elapsed.
        obs.reset_stats()
        b = _mk_batcher(tiny_model, max_batch=1, max_new_cap=256)
        res, _ = _drain(
            b,
            PROMPTS,
            budget=256,
            cancel_after=8,
            cancel_rows={0},
        )
        assert next(r for r in res if r.req_id == 0).cancelled
        assert next(r for r in res if r.req_id == 1).n_generated == 256
        steps = [
            e
            for e in obs.recorder.events()
            if e["type"] == "step" and e["kind"] != "prefill"
        ]
        # Without the cancel, req0 alone needs ~256/chunk decode steps
        # BEFORE req1 could even start; with it, the whole drain fits
        # in roughly req1's own budget of steps.
        assert len(steps) < (256 // b.chunk) + 4

    def test_cancelled_pages_freed_and_partial_prefix_cached(
        self, tiny_model
    ):
        b = _mk_batcher(tiny_model, max_batch=1, max_new_cap=96)
        prompt = [5, 6, 7, 8] * 40  # 160 tokens
        res, _ = _drain(
            b, [prompt], budget=96, cancel_after=40, cancel_rows={0}
        )
        r0 = res[0]
        assert r0.cancelled and r0.n_generated >= 40
        # All sequence refs dropped; only cache refs remain.
        assert b.allocator.free_pages > 0
        # Replay with the salvaged prefix: the adopted prefix must
        # extend PAST the prompt into the cancelled decode's tokens
        # (160 prompt tokens + the salvaged tail pages).
        res2, _ = _drain(
            b, [prompt + r0.tokens.tolist()], budget=16
        )
        covered = len(prompt) + r0.n_generated - 1
        expect = (covered // b.page_size) * b.page_size
        assert res2[0].cached_tokens >= min(expect, 192) > len(prompt)

    def test_cancel_obs_schema_and_no_recompiles(self, tiny_model):
        obs.reset_stats()
        obs.retrace.clear()
        b = _mk_batcher(tiny_model)
        _drain(b, PROMPTS, cancel_after=8, cancel_rows={0})
        events = obs.recorder.events()
        for e in events:
            assert obs.validate_event(e) == [], e
        cancels = [e for e in events if e["type"] == "cancel"]
        assert len(cancels) == 1
        assert cancels[0]["tokens_emitted"] >= 8
        spans = [
            e
            for e in events
            if e["type"] == "span"
            and e["name"] == "request"
            and e["phase"] == "cancelled"
        ]
        assert len(spans) == 1
        # Decomposition: cancelled envelope == prefill + decode spans.
        assert obs.snapshot()["retrace"]["unexpected_recompiles"] == 0

    def test_round_slo_judged_on_cancel(self, tiny_model):
        # A cancelled request still consumed service: a round-SLO
        # breach that happens to end in a cancel must count (and
        # self-capture) exactly as _finish_slot's does — regression
        # pin for the real-batcher slo_check on the cancel path.
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        obs.reset_stats()
        obs.configure(slo_round_s=1e-9)
        try:
            b = _mk_batcher(tiny_model)
            b.submit(
                SchedRequest(
                    req_id=0,
                    prompt_ids=PROMPTS[0],
                    max_new_tokens=48,
                    span_id="tr-001-00/s00",
                    on_tokens=lambda toks: len(toks) < 8,
                )
            )
            res = b.run_all()
            assert res[0].cancelled
            assert obs.slo_breaches().get("round") == 1
        finally:
            obs.configure(slo_round_s=0.0)

    def test_finished_row_not_cancelled(self, tiny_model):
        # A consumer that asks for cancellation AFTER its row already
        # finished (EOS/budget) must be a no-op: the row resolves as
        # finished, nothing to save.
        b = _mk_batcher(tiny_model, max_new_cap=4)
        res, delivered = _drain(
            b, PROMPTS, budget=4, cancel_after=1, cancel_rows={0}
        )
        r0 = next(r for r in res if r.req_id == 0)
        # Cancelled exactly at the first delivery point that found it
        # still active — or finished clean if it was already done.
        assert r0.n_generated >= 1
        b.allocator.check_invariants()


# -- tools render path ----------------------------------------------------


class TestToolsRender:
    def _dump_cancel_round(self, tmp_path):
        import dataclasses

        obs.reset_stats()
        sc = StreamScanner()
        # Stamp trace/span ids the way the debate layer does — the
        # per-request waterfall groups by span_id.
        req = dataclasses.replace(
            _agree_req(), trace_id="tr-001-00", span_id="tr-001-00/s00"
        )
        MockEngine().chat(
            [req],
            SamplingParams(),
            consumer=lambda r, t: sc.feed(t) is None,
        )
        path = tmp_path / "ev.jsonl"
        obs.dump_events(str(path))
        return path

    def test_obs_dump_renders_cancelled_request(self, tmp_path, capsys):
        from tools import obs_dump

        path = self._dump_cancel_round(tmp_path)
        rc = obs_dump.main([str(path), "--timeline", "--requests"])
        out = capsys.readouterr().out
        assert rc == 0  # every line schema-valid
        assert "early cancellation" in out
        assert "cancelled" in out

    def test_trace_view_decomposition_passes_on_cancel(
        self, tmp_path, capsys
    ):
        from tools import trace_view

        path = self._dump_cancel_round(tmp_path)
        rc = trace_view.main([str(path)])
        out = capsys.readouterr().out
        assert rc == 0  # decomposition check PASSES on the truncated set
        assert "CANCELLED" in out

    def test_bench_cancel_file_validates(self):
        from pathlib import Path

        from tools.bench_trend import validate_bench_file

        path = Path(__file__).resolve().parent.parent / "BENCH_cancel.json"
        if not path.exists():
            pytest.skip("BENCH_cancel.json not generated yet")
        row, problems = validate_bench_file(path)
        assert problems == [] and row is not None
        assert row["mode"] == "cancel"


# -- CLI plumbing ---------------------------------------------------------

SPEC = "# Spec\nA thing.\n"


class TestCliFlags:
    def _run(self, argv, stdin=SPEC):
        import io
        import sys as _sys

        from adversarial_spec_tpu import cli

        old = _sys.stdin
        _sys.stdin = io.StringIO(stdin)
        try:
            return cli.main(argv)
        finally:
            _sys.stdin = old

    def test_perf_stream_block_and_cancel(self, capsys):
        rc = self._run(
            [
                "critique",
                "-m",
                "mock://critic?agree_after=1&agree_tail=60",
                "--json",
            ]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        stream = out["perf"]["stream"]
        assert stream["enabled"] and stream["early_cancel"]
        assert stream["cancels"] == 1
        assert stream["tokens_saved"] > 0

    def test_no_stream_flag(self, capsys):
        rc = self._run(
            [
                "critique",
                "-m",
                "mock://critic?agree_after=1&agree_tail=60",
                "--no-stream",
                "--json",
            ]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        stream = out["perf"]["stream"]
        assert not stream["enabled"] and stream["cancels"] == 0
        # Full tail decoded: blocking path end to end.
        assert "remark 60" in out["results"][0]["response"]

    def test_no_early_cancel_flag(self, capsys):
        rc = self._run(
            [
                "critique",
                "-m",
                "mock://critic?agree_after=1&agree_tail=60",
                "--no-early-cancel",
                "--json",
            ]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["perf"]["stream"]["cancels"] == 0

    def test_env_default_and_no_leak(self, capsys, monkeypatch):
        monkeypatch.setenv("ADVSPEC_EARLY_CANCEL", "0")
        rc = self._run(
            [
                "critique",
                "-m",
                "mock://critic?agree_after=1&agree_tail=60",
                "--json",
            ]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert not out["perf"]["stream"]["early_cancel"]
        # Flag beats env; and the next invocation re-resolves (no leak).
        monkeypatch.delenv("ADVSPEC_EARLY_CANCEL")
        rc = self._run(
            [
                "critique",
                "-m",
                "mock://critic?agree_after=1&agree_tail=60",
                "--json",
            ]
        )
        out = json.loads(capsys.readouterr().out)
        assert out["perf"]["stream"]["early_cancel"]
        assert out["perf"]["stream"]["cancels"] == 1
