"""Telegram channel tests (reference analog: tests/test_telegram_bot.py —
mocked urlopen, chunk-boundary assertions, stepped clocks for polling)."""

import io
import json
import os
from unittest.mock import MagicMock, patch

import pytest

from adversarial_spec_tpu.debate import telegram
from adversarial_spec_tpu.debate.types import ModelResponse, RoundResult

CFG = telegram.TelegramConfig(token="tok", chat_id="42")


def _mock_urlopen(payloads):
    """urlopen mock returning successive JSON payloads as context managers."""
    responses = []
    for p in payloads:
        cm = MagicMock()
        cm.__enter__.return_value = io.BytesIO(json.dumps(p).encode())
        responses.append(cm)
    return MagicMock(side_effect=responses)


class TestConfig:
    def test_present(self, monkeypatch):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "t")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        cfg = telegram.get_config()
        assert cfg == telegram.TelegramConfig(token="t", chat_id="c")

    def test_missing(self, monkeypatch):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram.get_config() is None

    def test_blank_is_missing(self, monkeypatch):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "  ")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        assert telegram.get_config() is None


class TestApiCall:
    def test_ok_payload(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": True, "result": {"x": 1}}]),
        ) as m:
            out = telegram.api_call("tok", "sendMessage", {"a": "b"})
        assert out == {"x": 1}
        req = m.call_args[0][0]
        assert "bottok/sendMessage" in req.full_url
        assert m.call_args[1]["timeout"] == telegram.API_TIMEOUT_S

    def test_not_ok_raises(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": False, "description": "bad"}]),
        ):
            with pytest.raises(RuntimeError, match="sendMessage failed"):
                telegram.api_call("tok", "sendMessage")


class TestSplitMessage:
    def test_short_single_chunk(self):
        assert telegram.split_message("hello") == ["hello"]

    def test_empty(self):
        assert telegram.split_message("") == []

    def test_exact_limit_not_split(self):
        text = "x" * telegram.MAX_MESSAGE_LEN
        assert telegram.split_message(text) == [text]

    def test_over_limit_splits(self):
        text = "x" * (telegram.MAX_MESSAGE_LEN + 1)
        chunks = telegram.split_message(text)
        assert len(chunks) == 2
        assert all(len(c) <= telegram.MAX_MESSAGE_LEN for c in chunks)

    def test_prefers_paragraph_boundary(self):
        a = "a" * 3000
        b = "b" * 2000
        chunks = telegram.split_message(a + "\n\n" + b)
        assert chunks[0] == a
        assert chunks[1] == b

    def test_break_only_in_second_half(self):
        # A space at position 10 must NOT be used (first half of window).
        text = "y" * 10 + " " + "z" * 5000
        chunks = telegram.split_message(text, limit=100)
        assert len(chunks[0]) == 100

    def test_content_preserved(self):
        words = ("word " * 2000).strip()
        chunks = telegram.split_message(words, limit=500)
        assert "".join(chunks).replace("\n", " ").split() == words.split()


class TestSendLongMessage:
    def test_paced_chunks(self):
        sleeps = []
        sent = []
        with patch.object(
            telegram, "send_message", lambda cfg, text: sent.append(text)
        ):
            n = telegram.send_long_message(
                CFG, "a" * 5000, sleep=sleeps.append
            )
        assert n == 2 and len(sent) == 2
        assert sleeps == [telegram.CHUNK_PACING_S]  # no sleep after last


class TestPolling:
    def test_reply_from_right_chat(self):
        payloads = [
            {
                "ok": True,
                "result": [
                    {
                        "update_id": 7,
                        "message": {"chat": {"id": 99}, "text": "wrong chat"},
                    },
                    {
                        "update_id": 8,
                        "message": {"chat": {"id": 42}, "text": "do it"},
                    },
                ],
            }
        ]
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            reply = telegram.poll_for_reply(
                CFG, after_update_id=5, timeout_s=10
            )
        assert reply == "do it"

    def test_timeout_returns_none(self):
        clock_vals = iter([0.0, 0.0, 5.0, 11.0, 11.0])
        payloads = [{"ok": True, "result": []}] * 5
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            reply = telegram.poll_for_reply(
                CFG,
                after_update_id=0,
                timeout_s=10,
                clock=lambda: next(clock_vals),
            )
        assert reply is None

    def test_get_last_update_id(self):
        payloads = [
            {"ok": True, "result": [{"update_id": 3}, {"update_id": 9}]}
        ]
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            assert telegram.get_last_update_id(CFG) == 9

    def test_get_last_update_id_empty(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": True, "result": []}]),
        ):
            assert telegram.get_last_update_id(CFG) == 0


class TestCliSubcommands:
    def test_send(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        sent = []
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: sent.append(text)
        )
        assert telegram._cli(["send", "hello", "world"]) == 0
        assert sent == ["hello world"]

    def test_notify_with_reply(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 5)
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, timeout_s: "go ahead",
        )
        assert telegram._cli(["notify", "30", "round done"]) == 0
        assert "go ahead" in capsys.readouterr().out

    def test_notify_no_reply_exit_1(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 0)
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(
            telegram, "poll_for_reply", lambda cfg, after, timeout_s: None
        )
        assert telegram._cli(["notify", "5", "msg"]) == 1

    def test_unconfigured_exit_2(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram._cli(["send", "x"]) == 2

    def test_unknown_subcommand_exit_2(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        assert telegram._cli(["frobnicate"]) == 2


class TestRoundSummary:
    def test_format(self):
        result = RoundResult(
            responses=[
                ModelResponse(model="a", agreed=True, critique="[AGREE]"),
                ModelResponse(
                    model="b", critique="1. Needs error handling."
                ),
                ModelResponse(model="c", error="boom"),
            ],
            round_num=2,
        )
        text = telegram.format_round_summary(result, total_cost=0.12)
        assert "Debate round 2" in text
        assert "✓ a: AGREE" in text
        assert "Needs error handling" in text
        assert "✗ c: ERROR boom" in text
        assert "Debate continues." in text
        assert "$0.1200" in text

    def test_all_agree_banner(self):
        result = RoundResult(
            responses=[ModelResponse(model="a", agreed=True)], round_num=1
        )
        assert "All models agree!" in telegram.format_round_summary(result)


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors in
    telegram.py (tools/mutation_run.py; assertions name their mutants)."""

    def test_wire_constants_pinned(self):
        """Bot API base, the 4096 hard limit, 30 s timeout, pacing and
        poll-slice constants are protocol facts, not tunables."""
        assert telegram.API_BASE == "https://api.telegram.org"
        assert telegram.MAX_MESSAGE_LEN == 4096
        assert telegram.API_TIMEOUT_S == 30
        assert telegram.CHUNK_PACING_S == 0.5
        assert telegram.POLL_SLICE_S == 25

    def test_config_is_frozen(self):
        with pytest.raises(Exception):  # dataclasses.FrozenInstanceError
            CFG.token = "other"

    def test_api_error_message_names_method(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": False, "description": "bad"}]),
        ):
            with pytest.raises(
                RuntimeError, match=r"Telegram API getMe failed: "
            ):
                telegram.api_call("tok", "getMe")

    def test_split_exact_limit_is_one_chunk(self):
        """len == limit must NOT split (<= -> < mutant)."""
        text = "x" * 4096
        assert telegram.split_message(text) == [text]

    def test_split_tail_keeps_trailing_newline(self):
        """The final remainder is appended verbatim (the in-loop rstrip
        must not apply to it; > -> >= mutant on the loop guard)."""
        text = "a" * 4096 + "b" * 4095 + "\n"
        chunks = telegram.split_message(text)
        assert chunks == ["a" * 4096, "b" * 4095 + "\n"]

    def test_split_break_preference_order(self):
        """Paragraph beats line beats space (separator-string mutants)."""
        text = "A" * 5 + "\n\n" + "B" * 3 + "\nC D" + "E" * 12
        chunks = telegram.split_message(text, limit=12)
        # "\n\n" at idx 5 (> 12//2=6? no, 5 < 6) → "\n" at 10 wins.
        assert chunks[0] == "A" * 5 + "\n\n" + "B" * 3
        # Pure-paragraph case: "\n\n" in the second half is taken.
        t2 = "A" * 8 + "\n\n" + "B" * 8
        assert telegram.split_message(t2, limit=12)[0] == "A" * 8

    def test_split_break_only_in_second_half(self):
        """A separator at exactly limit//2 is NOT taken (> -> >= and
        //2 -> //3 mutants): the hard cut at limit wins."""
        text = "01234\n6789AB"
        chunks = telegram.split_message(text, limit=10)
        assert chunks == ["01234\n6789", "AB"]

    def test_split_rstrip_only_newlines(self):
        """Chunk trailing content other than newlines survives the
        rstrip (charset +XX mutant would eat literal X's)."""
        text = "AAAAAAX\n\n" + "B" * 10
        chunks = telegram.split_message(text, limit=12)
        assert chunks[0] == "AAAAAAX"

    def test_send_long_message_wire_format(self, monkeypatch):
        """Method name and param keys are the Bot API contract; pacing
        sleeps happen between chunks only."""
        sent = []
        sleeps = []
        monkeypatch.setattr(
            telegram,
            "api_call",
            lambda tok, method, params=None: sent.append(
                (tok, method, params)
            )
            or {},
        )
        n = telegram.send_long_message(
            CFG, "a" * 5000, sleep=sleeps.append
        )
        assert n == 2 and len(sent) == 2
        for tok, method, params in sent:
            assert tok == "tok"
            assert method == "sendMessage"
            assert set(params) == {"chat_id", "text"}
            assert params["chat_id"] == "42"
        assert sleeps == [telegram.CHUNK_PACING_S]

    def test_get_last_update_id_wire_and_defaults(self, monkeypatch):
        calls = []

        def fake(tok, method, params=None):
            calls.append((method, params))
            return [{"update_id": 7}, {}]

        monkeypatch.setattr(telegram, "api_call", fake)
        assert telegram.get_last_update_id(CFG) == 7
        assert calls == [("getUpdates", {"timeout": 0})]
        # Missing update_id fields default to 0, empty list gives 0.
        monkeypatch.setattr(
            telegram, "api_call", lambda *a, **k: [{}]
        )
        assert telegram.get_last_update_id(CFG) == 0
        monkeypatch.setattr(telegram, "api_call", lambda *a, **k: [])
        assert telegram.get_last_update_id(CFG) == 0

    def test_poll_zero_timeout_never_calls_api(self, monkeypatch):
        """deadline uses a strict < (LtE mutant would fire one call)."""
        monkeypatch.setattr(
            telegram,
            "api_call",
            lambda *a, **k: pytest.fail("api_call with zero budget"),
        )
        t = 1000.0
        assert (
            telegram.poll_for_reply(CFG, 5, 0, clock=lambda: t) is None
        )

    def test_poll_offset_and_slice_wire(self, monkeypatch):
        """offset starts at after_update_id + 1; the slice is
        min(POLL_SLICE_S, remaining) and never below 1 s."""
        seen = []

        def fake(tok, method, params=None):
            seen.append(dict(params))
            return [
                {
                    "update_id": 11,
                    "message": {"chat": {"id": 42}, "text": "yo"},
                }
            ]

        monkeypatch.setattr(telegram, "api_call", fake)
        out = telegram.poll_for_reply(CFG, 5, 100, clock=lambda: 1000.0)
        assert out == "yo"
        assert seen == [{"timeout": 25, "offset": 6}]
        # Sub-second remaining budget clamps the slice UP to 1.
        seen.clear()
        telegram.poll_for_reply(CFG, 5, 0.5, clock=lambda: 1000.0)
        assert seen[0]["timeout"] == 1

    def test_poll_advances_offset_past_seen_updates(self, monkeypatch):
        ticks = iter([0.0, 1.0, 2.0, 3.0, 4.0, 5.0])
        responses = iter(
            [
                [
                    {
                        "update_id": 30,
                        "message": {"chat": {"id": 99}, "text": "other"},
                    }
                ],
                [
                    {
                        "update_id": 31,
                        "message": {"chat": {"id": 42}, "text": "mine"},
                    }
                ],
            ]
        )
        seen = []

        def fake(tok, method, params=None):
            seen.append(dict(params))
            return next(responses)

        monkeypatch.setattr(telegram, "api_call", fake)
        out = telegram.poll_for_reply(
            CFG, 5, 60, clock=lambda: next(ticks)
        )
        assert out == "mine"
        assert [p["offset"] for p in seen] == [6, 31]

    def test_discover_prefers_latest_and_skips_chatless(self, monkeypatch):
        updates = [
            {"message": None},
            {"message": {"chat": {"id": 5}}},
            {"message": {"chat": {}}},
        ]
        monkeypatch.setattr(
            telegram, "api_call", lambda *a, **k: updates
        )
        assert telegram.discover_chat_id("tok") == "5"
        monkeypatch.setattr(telegram, "api_call", lambda *a, **k: [])
        assert telegram.discover_chat_id("tok") is None

    def test_round_summary_exact_text(self):
        long_critique = "c" * 200
        result = RoundResult(
            responses=[
                ModelResponse(model="m1", agreed=True),
                ModelResponse(model="m2", error="boom"),
                ModelResponse(model="m3", critique=long_critique),
            ],
            round_num=4,
        )
        out = telegram.format_round_summary(result, total_cost=1.5)
        lines = out.split("\n")
        assert lines[0] == "Debate round 4:"
        assert lines[1] == "  ✓ m1: AGREE"
        assert lines[2] == "  ✗ m2: ERROR boom"
        assert lines[3] == "  … m3: " + "c" * 117 + "..."
        assert lines[4] == "Debate continues."
        assert lines[5] == "Cost so far: $1.5000"
        agreed = RoundResult(
            responses=[ModelResponse(model="m1", agreed=True)]
        )
        assert "All models agree!" in telegram.format_round_summary(agreed)

    def test_notify_round_no_feedback_skips_polling(self, monkeypatch):
        sent = []
        monkeypatch.setattr(
            telegram,
            "send_long_message",
            lambda cfg, text: sent.append(text) or 1,
        )
        monkeypatch.setattr(
            telegram,
            "get_last_update_id",
            lambda cfg: pytest.fail("polled with feedback_timeout=0"),
        )
        result = RoundResult(responses=[ModelResponse(model="m")])
        assert telegram.notify_round(CFG, result) is None
        assert len(sent) == 1

    def test_notify_round_feedback_prompt_and_reply(self, monkeypatch):
        prompts = []
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(
            telegram,
            "send_message",
            lambda cfg, text: prompts.append(text),
        )
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 9)
        polled = []
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, t: polled.append((after, t)) or "fb",
        )
        result = RoundResult(responses=[ModelResponse(model="m")])
        out = telegram.notify_round(CFG, result, feedback_timeout=1)
        assert out == "fb"
        assert polled == [(9, 1)]
        assert prompts == [
            "Reply within 1s to inject feedback into the next round."
        ]


class TestCliMutationHardening:
    """_cli return codes, argument parsing, and user-facing strings."""

    def _env(self, monkeypatch, token="tok", chat="42"):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", token)
        monkeypatch.setenv("TELEGRAM_CHAT_ID", chat)

    def test_no_args_usage(self, capsys):
        assert telegram._cli([]) == 2
        assert "usage: telegram" in capsys.readouterr().err

    def test_setup_success(self, monkeypatch, capsys):
        self._env(monkeypatch)
        monkeypatch.setattr(
            telegram, "discover_chat_id", lambda tok: "777"
        )
        assert telegram._cli(["setup"]) == 0
        assert (
            "export TELEGRAM_CHAT_ID=777" in capsys.readouterr().out
        )

    def test_setup_without_token(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        assert telegram._cli(["setup"]) == 2
        assert "set TELEGRAM_BOT_TOKEN" in capsys.readouterr().err

    def test_setup_no_messages(self, monkeypatch, capsys):
        self._env(monkeypatch)
        monkeypatch.setattr(
            telegram, "discover_chat_id", lambda tok: None
        )
        assert telegram._cli(["setup"]) == 1
        assert "no messages found" in capsys.readouterr().err

    def test_missing_config_error(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram._cli(["send", "x"]) == 2
        assert (
            "set TELEGRAM_BOT_TOKEN and TELEGRAM_CHAT_ID"
            in capsys.readouterr().err
        )

    def test_send_joins_args(self, monkeypatch):
        self._env(monkeypatch)
        sent = []
        monkeypatch.setattr(
            telegram,
            "send_long_message",
            lambda cfg, text: sent.append(text) or 1,
        )
        assert telegram._cli(["send", "hello", "world"]) == 0
        assert sent == ["hello world"]

    def test_poll_default_and_explicit_timeout(self, monkeypatch, capsys):
        self._env(monkeypatch)
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 3)
        polled = []
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, t: polled.append((after, t)) or "answer",
        )
        assert telegram._cli(["poll"]) == 0
        assert telegram._cli(["poll", "5"]) == 0
        assert polled == [(3, 60), (3, 5)]
        assert capsys.readouterr().out == "answer\nanswer\n"

    def test_poll_no_reply(self, monkeypatch, capsys):
        self._env(monkeypatch)
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 3)
        monkeypatch.setattr(
            telegram, "poll_for_reply", lambda cfg, after, t: None
        )
        assert telegram._cli(["poll", "1"]) == 1
        assert "(no reply)" in capsys.readouterr().err

    def test_notify_text_only_never_polls(self, monkeypatch):
        self._env(monkeypatch)
        sent = []
        monkeypatch.setattr(
            telegram,
            "send_long_message",
            lambda cfg, text: sent.append(text) or 1,
        )
        monkeypatch.setattr(
            telegram,
            "get_last_update_id",
            lambda cfg: pytest.fail("polled in text-only notify"),
        )
        assert telegram._cli(["notify", "plain", "text"]) == 0
        assert sent == ["plain text"]

    def test_notify_numeric_timeout_polls(self, monkeypatch, capsys):
        self._env(monkeypatch)
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 8)
        polled = []
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, t: polled.append((after, t)) or "ok",
        )
        assert telegram._cli(["notify", "1", "msg"]) == 0
        assert polled == [(8, 1)]
        assert capsys.readouterr().out == "ok\n"
        monkeypatch.setattr(
            telegram, "poll_for_reply", lambda cfg, after, t: None
        )
        assert telegram._cli(["notify", "1", "msg"]) == 1

    def test_unknown_subcommand(self, monkeypatch, capsys):
        self._env(monkeypatch)
        assert telegram._cli(["bogus"]) == 2
        assert "unknown subcommand 'bogus'" in capsys.readouterr().err


class TestMutationHardeningRound2:
    """Second-pass pins: survivors whose first-pass assertions used
    substring matches that `+XX` mutants slip past, plus wire params
    the lambda mocks ignored."""

    def test_api_error_message_exact_shape(self):
        """The payload dict follows the labeled method immediately."""
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": False, "description": "bad"}]),
        ):
            with pytest.raises(
                RuntimeError, match=r"Telegram API getMe failed: \{"
            ):
                telegram.api_call("tok", "getMe")

    def test_split_separator_strings_exact(self):
        """Paragraph and space separators are the literal two-char/one-
        char strings (a mutated separator silently degrades every break
        to the hard cut)."""
        # Paragraph break in the second half; a line break sits later,
        # so a broken "\n\n" separator would cut at the "\n" instead.
        text = "A" * 7 + "\n\n" + "B\n" + "C" * 10
        assert telegram.split_message(text, limit=12)[0] == "A" * 7
        # Space break: no newlines at all.
        t2 = "A" * 7 + " " + "B" * 10
        chunks = telegram.split_message(t2, limit=12)
        assert chunks == ["A" * 7 + " ", "B" * 10]

    def test_poll_method_name_and_unidentified_updates(self, monkeypatch):
        """getUpdates is the method on every slice; an update missing
        update_id must not advance the offset past 0+1."""
        calls = []
        responses = iter(
            [
                [{"message": {"chat": {"id": 99}, "text": "other"}}],
                [
                    {
                        "update_id": 3,
                        "message": {"chat": {"id": 42}, "text": "mine"},
                    }
                ],
            ]
        )

        def fake(tok, method, params=None):
            calls.append((method, dict(params)))
            return next(responses)

        monkeypatch.setattr(telegram, "api_call", fake)
        import itertools

        ticks = (float(i) for i in itertools.count())
        out = telegram.poll_for_reply(CFG, 0, 60, clock=lambda: next(ticks))
        assert out == "mine"
        assert [m for m, _ in calls] == ["getUpdates", "getUpdates"]
        assert [p["offset"] for _, p in calls] == [1, 1]

    def test_discover_wire_params(self, monkeypatch):
        calls = []

        def fake(tok, method, params=None):
            calls.append((tok, method, dict(params)))
            return [{"message": {"chat": {"id": 5}}}]

        monkeypatch.setattr(telegram, "api_call", fake)
        assert telegram.discover_chat_id("tok") == "5"
        assert calls == [("tok", "getUpdates", {"timeout": 0})]

    def test_all_agree_line_exact(self):
        agreed = RoundResult(
            responses=[ModelResponse(model="m1", agreed=True)]
        )
        out = telegram.format_round_summary(agreed)
        assert out.split("\n")[-1] == "All models agree!"

    def test_cli_error_lines_exact(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        monkeypatch.setattr(telegram, "discover_chat_id", lambda tok: None)
        assert telegram._cli(["setup"]) == 1
        assert capsys.readouterr().err == (
            "no messages found — send your bot a message, then rerun\n"
        )
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram._cli(["send", "x"]) == 2
        assert capsys.readouterr().err == (
            "error: set TELEGRAM_BOT_TOKEN and TELEGRAM_CHAT_ID\n"
        )

    def test_module_entrypoint(self):
        """python -m …telegram runs _cli on argv[1:] (pins the
        __main__ guard and the argv slice)."""
        import subprocess
        import sys as _sys
        from pathlib import Path

        if os.environ.get("ADVSPEC_MUTATION") == "1":
            pytest.skip("interpreter boot per mutant; pinned outside sweeps")
        repo_root = str(Path(__file__).resolve().parent.parent)
        r = subprocess.run(
            [_sys.executable, "-m", "adversarial_spec_tpu.debate.telegram",
             "bogus"],
            capture_output=True,
            text=True,
            env={**os.environ, "TELEGRAM_BOT_TOKEN": "t",
                 "TELEGRAM_CHAT_ID": "c", "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo_root},
            timeout=120,
        )
        assert r.returncode == 2
        assert "unknown subcommand 'bogus'" in r.stderr
