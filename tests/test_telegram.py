"""Telegram channel tests (reference analog: tests/test_telegram_bot.py —
mocked urlopen, chunk-boundary assertions, stepped clocks for polling)."""

import io
import json
from unittest.mock import MagicMock, patch

import pytest

from adversarial_spec_tpu.debate import telegram
from adversarial_spec_tpu.debate.types import ModelResponse, RoundResult

CFG = telegram.TelegramConfig(token="tok", chat_id="42")


def _mock_urlopen(payloads):
    """urlopen mock returning successive JSON payloads as context managers."""
    responses = []
    for p in payloads:
        cm = MagicMock()
        cm.__enter__.return_value = io.BytesIO(json.dumps(p).encode())
        responses.append(cm)
    return MagicMock(side_effect=responses)


class TestConfig:
    def test_present(self, monkeypatch):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "t")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        cfg = telegram.get_config()
        assert cfg == telegram.TelegramConfig(token="t", chat_id="c")

    def test_missing(self, monkeypatch):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram.get_config() is None

    def test_blank_is_missing(self, monkeypatch):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "  ")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "c")
        assert telegram.get_config() is None


class TestApiCall:
    def test_ok_payload(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": True, "result": {"x": 1}}]),
        ) as m:
            out = telegram.api_call("tok", "sendMessage", {"a": "b"})
        assert out == {"x": 1}
        req = m.call_args[0][0]
        assert "bottok/sendMessage" in req.full_url
        assert m.call_args[1]["timeout"] == telegram.API_TIMEOUT_S

    def test_not_ok_raises(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": False, "description": "bad"}]),
        ):
            with pytest.raises(RuntimeError, match="sendMessage failed"):
                telegram.api_call("tok", "sendMessage")


class TestSplitMessage:
    def test_short_single_chunk(self):
        assert telegram.split_message("hello") == ["hello"]

    def test_empty(self):
        assert telegram.split_message("") == []

    def test_exact_limit_not_split(self):
        text = "x" * telegram.MAX_MESSAGE_LEN
        assert telegram.split_message(text) == [text]

    def test_over_limit_splits(self):
        text = "x" * (telegram.MAX_MESSAGE_LEN + 1)
        chunks = telegram.split_message(text)
        assert len(chunks) == 2
        assert all(len(c) <= telegram.MAX_MESSAGE_LEN for c in chunks)

    def test_prefers_paragraph_boundary(self):
        a = "a" * 3000
        b = "b" * 2000
        chunks = telegram.split_message(a + "\n\n" + b)
        assert chunks[0] == a
        assert chunks[1] == b

    def test_break_only_in_second_half(self):
        # A space at position 10 must NOT be used (first half of window).
        text = "y" * 10 + " " + "z" * 5000
        chunks = telegram.split_message(text, limit=100)
        assert len(chunks[0]) == 100

    def test_content_preserved(self):
        words = ("word " * 2000).strip()
        chunks = telegram.split_message(words, limit=500)
        assert "".join(chunks).replace("\n", " ").split() == words.split()


class TestSendLongMessage:
    def test_paced_chunks(self):
        sleeps = []
        sent = []
        with patch.object(
            telegram, "send_message", lambda cfg, text: sent.append(text)
        ):
            n = telegram.send_long_message(
                CFG, "a" * 5000, sleep=sleeps.append
            )
        assert n == 2 and len(sent) == 2
        assert sleeps == [telegram.CHUNK_PACING_S]  # no sleep after last


class TestPolling:
    def test_reply_from_right_chat(self):
        payloads = [
            {
                "ok": True,
                "result": [
                    {
                        "update_id": 7,
                        "message": {"chat": {"id": 99}, "text": "wrong chat"},
                    },
                    {
                        "update_id": 8,
                        "message": {"chat": {"id": 42}, "text": "do it"},
                    },
                ],
            }
        ]
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            reply = telegram.poll_for_reply(
                CFG, after_update_id=5, timeout_s=10
            )
        assert reply == "do it"

    def test_timeout_returns_none(self):
        clock_vals = iter([0.0, 0.0, 5.0, 11.0, 11.0])
        payloads = [{"ok": True, "result": []}] * 5
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            reply = telegram.poll_for_reply(
                CFG,
                after_update_id=0,
                timeout_s=10,
                clock=lambda: next(clock_vals),
            )
        assert reply is None

    def test_get_last_update_id(self):
        payloads = [
            {"ok": True, "result": [{"update_id": 3}, {"update_id": 9}]}
        ]
        with patch.object(
            telegram.urllib.request, "urlopen", _mock_urlopen(payloads)
        ):
            assert telegram.get_last_update_id(CFG) == 9

    def test_get_last_update_id_empty(self):
        with patch.object(
            telegram.urllib.request,
            "urlopen",
            _mock_urlopen([{"ok": True, "result": []}]),
        ):
            assert telegram.get_last_update_id(CFG) == 0


class TestCliSubcommands:
    def test_send(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        sent = []
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: sent.append(text)
        )
        assert telegram._cli(["send", "hello", "world"]) == 0
        assert sent == ["hello world"]

    def test_notify_with_reply(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 5)
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(
            telegram,
            "poll_for_reply",
            lambda cfg, after, timeout_s: "go ahead",
        )
        assert telegram._cli(["notify", "30", "round done"]) == 0
        assert "go ahead" in capsys.readouterr().out

    def test_notify_no_reply_exit_1(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        monkeypatch.setattr(telegram, "get_last_update_id", lambda cfg: 0)
        monkeypatch.setattr(
            telegram, "send_long_message", lambda cfg, text: 1
        )
        monkeypatch.setattr(
            telegram, "poll_for_reply", lambda cfg, after, timeout_s: None
        )
        assert telegram._cli(["notify", "5", "msg"]) == 1

    def test_unconfigured_exit_2(self, monkeypatch, capsys):
        monkeypatch.delenv("TELEGRAM_BOT_TOKEN", raising=False)
        monkeypatch.delenv("TELEGRAM_CHAT_ID", raising=False)
        assert telegram._cli(["send", "x"]) == 2

    def test_unknown_subcommand_exit_2(self, monkeypatch, capsys):
        monkeypatch.setenv("TELEGRAM_BOT_TOKEN", "tok")
        monkeypatch.setenv("TELEGRAM_CHAT_ID", "42")
        assert telegram._cli(["frobnicate"]) == 2


class TestRoundSummary:
    def test_format(self):
        result = RoundResult(
            responses=[
                ModelResponse(model="a", agreed=True, critique="[AGREE]"),
                ModelResponse(
                    model="b", critique="1. Needs error handling."
                ),
                ModelResponse(model="c", error="boom"),
            ],
            round_num=2,
        )
        text = telegram.format_round_summary(result, total_cost=0.12)
        assert "Debate round 2" in text
        assert "✓ a: AGREE" in text
        assert "Needs error handling" in text
        assert "✗ c: ERROR boom" in text
        assert "Debate continues." in text
        assert "$0.1200" in text

    def test_all_agree_banner(self):
        result = RoundResult(
            responses=[ModelResponse(model="a", agreed=True)], round_num=1
        )
        assert "All models agree!" in telegram.format_round_summary(result)
