"""Tokenizer tests: byte fallback, HF tokenizer.json (built
programmatically — zero downloads), chat templates, and the engine's
context-budget truncation."""

from pathlib import Path

import pytest

from adversarial_spec_tpu.engine.tokenizer import (
    ByteTokenizer,
    CHAT_TEMPLATES,
    GENERIC_CHAT_TEMPLATE,
    HFTokenizer,
    apply_chat_template,
    load_tokenizer,
)


@pytest.fixture(scope="module")
def hf_tokenizer_file(tmp_path_factory):
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        special_tokens=["<unk>", "<s>", "</s>", "<|eot_id|>"],
        vocab_size=200,
    )
    tok.train_from_iterator(
        [
            "the quick brown fox jumps over the lazy dog " * 3,
            "spec review critique agree revise document " * 3,
        ],
        trainer,
    )
    path = tmp_path_factory.mktemp("tok") / "tokenizer.json"
    tok.save(str(path))
    return str(path)


class TestByteTokenizer:
    def test_roundtrip(self):
        t = ByteTokenizer()
        ids = t.encode("hello ✓", add_bos=False)
        assert t.decode(ids) == "hello ✓"

    def test_bos_prepended(self):
        t = ByteTokenizer()
        assert t.encode("a")[0] == t.bos_id

    def test_out_of_range_ids_skipped(self):
        t = ByteTokenizer()
        assert t.decode([1, 400, 104 + 3, 105 + 3]) == "hi"

    def test_specials(self):
        t = ByteTokenizer()
        assert t.pad_id == 0 and t.bos_id == 1 and t.eos_ids == [2]


class TestHFTokenizer:
    def test_load_from_file_and_dir(self, hf_tokenizer_file):
        t = HFTokenizer(hf_tokenizer_file)
        assert t.vocab_size > 0
        import pathlib

        t2 = HFTokenizer(str(pathlib.Path(hf_tokenizer_file).parent))
        assert t2.vocab_size == t.vocab_size

    def test_roundtrip(self, hf_tokenizer_file):
        t = HFTokenizer(hf_tokenizer_file)
        ids = t.encode("critique the spec", add_bos=False)
        assert len(ids) >= 3
        assert t.decode(ids) == "critique the spec"

    def test_specials_detected(self, hf_tokenizer_file):
        t = HFTokenizer(hf_tokenizer_file)
        # <s> is a BOS candidate; </s> and <|eot_id|> are both EOS markers.
        assert t.bos_id is not None
        assert len(t.eos_ids) == 2

    def test_bos_prepended(self, hf_tokenizer_file):
        t = HFTokenizer(hf_tokenizer_file)
        with_bos = t.encode("spec")
        without = t.encode("spec", add_bos=False)
        assert with_bos == [t.bos_id] + without

    def test_factory(self, hf_tokenizer_file):
        assert isinstance(load_tokenizer(""), ByteTokenizer)
        assert isinstance(load_tokenizer(hf_tokenizer_file), HFTokenizer)


class TestChatTemplates:
    def test_generic_for_base_models(self):
        out = apply_chat_template("llama", "SYS", "USER", instruct=False)
        assert out == GENERIC_CHAT_TEMPLATE.format(system="SYS", user="USER")

    @pytest.mark.parametrize("family", sorted(CHAT_TEMPLATES))
    def test_family_templates_render(self, family):
        out = apply_chat_template(family, "SYS", "USER", instruct=True)
        assert "SYS" in out and "USER" in out
        assert out != GENERIC_CHAT_TEMPLATE.format(system="SYS", user="USER")

    def test_unknown_family_falls_back(self):
        out = apply_chat_template("falcon", "S", "U", instruct=True)
        assert out == GENERIC_CHAT_TEMPLATE.format(system="S", user="U")


class TestPromptTruncation:
    def test_long_prompt_truncated_to_context_budget(self, monkeypatch):
        """The engine must clamp prompts so prompt + max_new fits the
        model context, keeping the BOS and the prompt TAIL (the most
        recent document content)."""
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )
        from adversarial_spec_tpu.engine.tpu import TpuEngine
        from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

        save_registry_entry(
            ModelSpec(
                alias="small-ctx",
                family="llama",
                size="tiny",
                dtype="float32",
                max_seq_len=256,
            )
        )
        eng = TpuEngine()
        captured = {}
        import adversarial_spec_tpu.engine.tpu as tpu_mod

        real_generate = tpu_mod.generate

        def spy(params, cfg, prompts, **kw):
            captured["prompt_lens"] = [len(p) for p in prompts]
            return real_generate(params, cfg, prompts, **kw)

        monkeypatch.setattr(tpu_mod, "generate", spy)
        comp = eng.chat(
            [
                ChatRequest(
                    model="tpu://small-ctx", system="s", user="x " * 2000
                )
            ],
            SamplingParams(max_new_tokens=64, greedy=True),
        )[0]
        assert comp.ok, comp.error
        # budget = 256 - 64 = 192 tokens max for the prompt.
        assert captured["prompt_lens"][0] <= 192


class TestGoldenChatTemplates:
    """Golden parity: the engine's ``.format``-string CHAT_TEMPLATES vs
    the families' PUBLIC jinja chat templates rendered by transformers'
    OWN machinery (``render_jinja_template`` — the exact code
    ``PreTrainedTokenizer.apply_chat_template`` calls). VERDICT r4
    item 6: a silent template mismatch on real instruct checkpoints
    would degrade critique quality with no failing test — this pins it.

    The vendored .jinja fixtures (tests/fixtures/chat_templates/) are
    the templates shipped in the public tokenizer_config.json of
    Llama-3-Instruct, Mistral-7B-Instruct-v0.2, gemma-2-it and
    Qwen2-Instruct. String-identical prompts imply token-identical ids
    under the family tokenizer (same text, same tokenizer); the BOS
    token the jinja templates inline is added by ``encode(add_bos=True)``
    on the engine side, so the assertion is
    ``bos_token + engine_render == hf_render``.

    Family conventions the engine must reproduce:
    - mistral / gemma-2 have NO system role — the public convention
      (mistral-common; gemma model card) folds the system prompt into
      the first user turn separated by a blank line;
    - qwen2 takes the system turn verbatim (no BOS token at all);
    - the debate engine always sends a non-empty system prompt
      (debate/prompts.py), so the empty-system default-injection path
      of qwen2's template is out of scope.
    """

    FIXTURES = Path(__file__).parent / "fixtures" / "chat_templates"
    SYSTEM = "You are a ruthless spec critic."
    USER = "# PRD\nShip the thing.\n\nCritique this spec."

    def _render_hf(self, fixture, messages, **special):
        ctu = pytest.importorskip(
            "transformers.utils.chat_template_utils",
            reason="needs transformers with render_jinja_template",
        )
        render_jinja_template = getattr(ctu, "render_jinja_template", None)
        if render_jinja_template is None:
            pytest.skip("transformers too old: no render_jinja_template")

        template = (self.FIXTURES / fixture).read_text().rstrip("\n")
        rendered, _ = render_jinja_template(
            conversations=[messages],
            chat_template=template,
            add_generation_prompt=True,
            **special,
        )
        return rendered[0] if isinstance(rendered, list) else rendered

    @pytest.mark.parametrize(
        "family,fixture,bos",
        [
            ("llama", "llama3.jinja", "<|begin_of_text|>"),
            ("mistral", "mistral.jinja", "<s>"),
            ("gemma2", "gemma2.jinja", "<bos>"),
            ("qwen2", "qwen2.jinja", ""),
        ],
    )
    def test_engine_matches_public_template(self, family, fixture, bos):
        if family in ("mistral", "gemma2"):
            # No system role in the public template: fold into the
            # first user turn (the engine template does the same).
            messages = [
                {
                    "role": "user",
                    "content": f"{self.SYSTEM}\n\n{self.USER}",
                }
            ]
        else:
            messages = [
                {"role": "system", "content": self.SYSTEM},
                {"role": "user", "content": self.USER},
            ]
        hf = self._render_hf(
            fixture, messages, bos_token=bos, eos_token="</s>"
        )
        engine = apply_chat_template(
            family, self.SYSTEM, self.USER, instruct=True
        )
        assert bos + engine == hf
