"""Harvest-analysis tools: MIN_T recommendation, tuned-env extraction,
and bench.py's application of both."""

import json
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.crossover_report import (  # noqa: E402
    load,
    recommended_env,
    recommended_min_t,
)


def _steps(rows):
    return {r["step"]: r for r in rows}


class TestRecommendedMinT:
    def test_kernel_wins_everywhere(self):
        steps = _steps(
            [
                {"step": f"crossover_T{t}_kernel", "decode_tok_s": 500},
                {"step": f"crossover_T{t}_xla", "decode_tok_s": 400},
            ][i]
            for t in (1280, 4096)
            for i in (0, 1)
        )
        assert recommended_min_t(steps) == 0

    def test_clean_crossover(self):
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 380},
                {"step": "crossover_T1280_xla", "decode_tok_s": 490},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 400},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
                {"step": "crossover_T8192_kernel", "decode_tok_s": 280},
                {"step": "crossover_T8192_xla", "decode_tok_s": 150},
            ]
        )
        assert recommended_min_t(steps) == 4096

    def test_kernel_never_wins(self):
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 300},
                {"step": "crossover_T1280_xla", "decode_tok_s": 490},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 200},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
            ]
        )
        assert recommended_min_t(steps) == 1 << 31  # kernel off

    def test_mid_loss_resets_suffix(self):
        """kernel wins at 1280, loses at 4096, wins at 8192 → floor is
        8192 (the clean winning suffix), never 1280."""
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 500},
                {"step": "crossover_T1280_xla", "decode_tok_s": 400},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 200},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
                {"step": "crossover_T8192_kernel", "decode_tok_s": 400},
                {"step": "crossover_T8192_xla", "decode_tok_s": 300},
            ]
        )
        assert recommended_min_t(steps) == 8192

    def test_no_data(self):
        assert recommended_min_t({}) is None


class TestRecommendedEnv:
    def test_sweep_beats_default(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "chunk64", "decode_tok_s": 450},
                {"step": "chunk256", "decode_tok_s": 560},
                {"step": "unroll1", "decode_tok_s": 480},
                {"step": "unroll2", "decode_tok_s": 490},
            ]
        )
        env = recommended_env(steps)
        assert env["ADVSPEC_DECODE_CHUNK"] == "256"
        assert "ADVSPEC_DECODE_UNROLL" not in env  # default 4 won

    def test_defaults_win_yields_no_overrides(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "chunk64", "decode_tok_s": 450},
                {"step": "unroll1", "decode_tok_s": 400},
            ]
        )
        assert recommended_env(steps) == {}

    def test_spec_off_beating_spec_on_sets_kill_switch(self):
        """The comparison uses the PINNED spec_on/spec_off pair, not
        north_star (whose speculation default is governed by the very
        env var being recommended — a north_star baseline would flap)."""
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 560},
                {"step": "spec_on", "decode_tok_s": 500},
                {"step": "spec_off", "decode_tok_s": 550},
            ]
        )
        assert recommended_env(steps)["ADVSPEC_SPECULATIVE"] == "0"

    def test_spec_off_losing_keeps_speculation(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "spec_on", "decode_tok_s": 500},
                {"step": "spec_off", "decode_tok_s": 400},
            ]
        )
        assert "ADVSPEC_SPECULATIVE" not in recommended_env(steps)


class TestBenchAppliesHarvest:
    def test_harvested_tuning_reads_latest_jsonl(self, tmp_path,
                                                 monkeypatch):
        import bench

        rows = [
            {"step": "north_star", "decode_tok_s": 500},
            {"step": "chunk256", "decode_tok_s": 600},
            {"step": "crossover_T1280_kernel", "decode_tok_s": 380},
            {"step": "crossover_T1280_xla", "decode_tok_s": 490},
            {"step": "crossover_T4096_kernel", "decode_tok_s": 400},
            {"step": "crossover_T4096_xla", "decode_tok_s": 300},
        ]
        results = tmp_path / "tpu_results"
        results.mkdir()
        (results / "r04.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows)
        )
        # Point bench at the temp repo layout.
        monkeypatch.setattr(
            bench.os.path, "abspath", lambda p: str(tmp_path / "bench.py")
        )
        env = bench._harvested_tuning()
        assert env["ADVSPEC_DECODE_CHUNK"] == "256"
        assert env["ADVSPEC_PALLAS_MIN_T"] == "4096"

    def test_no_harvest_is_empty(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setattr(
            bench.os.path, "abspath", lambda p: str(tmp_path / "bench.py")
        )
        assert bench._harvested_tuning() == {}

    @pytest.mark.slow
    def test_round_loop_mode_runs(self):
        """The config-4-shaped bench mode produces a complete record
        (driver-facing surface; pinned so the mode can't rot)."""
        import bench

        out = bench._run_round_loop("cpu")
        assert out["rounds"] == 5
        assert out["decode_tokens_total"] == 5 * 4 * 256
        assert out["value"] > 0
        assert out["vs_baseline"] is None  # cpu: no north-star ratio

    def test_load_tolerates_junk_lines(self, tmp_path):
        p = tmp_path / "r.jsonl"
        p.write_text('not json\n{"step": "north_star", '
                     '"decode_tok_s": 1}\n')
        assert load(str(p))["north_star"]["decode_tok_s"] == 1


class TestAstLint:
    """tools/astlint.py — the locally-executable typecheck gate
    (reference ci.yml runs mypy; this runs everywhere, deps-free)."""

    def test_repo_is_clean(self):
        """The package + tools + entry scripts lint clean. This is the
        executed typecheck VERDICT r4 item 5 asked for — run here on
        every test invocation, not just in CI."""
        import subprocess

        r = subprocess.run(
            [sys.executable, str(REPO_ROOT / "tools" / "astlint.py")],
            capture_output=True,
            text=True,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        # The gate must actually be checking something.
        assert "call sites arity-checked" in r.stderr
        checked = int(r.stderr.rsplit("(", 1)[1].split()[0])
        assert checked > 400

    def test_scheduler_sync_rule_can_fire(self, monkeypatch):
        """The block_until_ready rule is a live gate: the real batcher
        DOES sync inside its allowlisted methods, so emptying the
        allowlist must produce findings — and the default allowlist must
        produce none (the repo-clean test covers the latter end to end,
        this pins that the rule is doing the exempting)."""
        import tools.astlint as astlint

        files = [
            REPO_ROOT / "adversarial_spec_tpu" / "engine" / "scheduler.py"
        ]
        index = {
            astlint._modname_for(f): astlint._collect_module(
                f, astlint._modname_for(f)
            )
            for f in files
        }
        findings: list[str] = []
        astlint.check_scheduler_sync(index, findings)
        assert findings == []
        monkeypatch.setattr(astlint, "_SCHEDULER_SYNC_ALLOWLIST", set())
        astlint.check_scheduler_sync(index, findings)
        assert findings, "emptied allowlist produced no findings"
        assert all("block_until_ready" in f for f in findings)
        # Both sanctioned sync points really are the ones syncing.
        assert any("_advance_admission" in f for f in findings)
        assert any("_drive_legacy" in f for f in findings)

    def test_detects_seeded_error_classes(self, tmp_path, monkeypatch):
        """Every advertised error class fires on a synthetic package —
        proof the gate can fail (a gate that can't fail is not a gate)."""
        import importlib

        import tools.astlint as astlint

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "good.py").write_text(
            "def takes_two(a, b, *, c=0):\n    return a\n"
        )
        (pkg / "bad.py").write_text(
            "from pkg.good import takes_two, absent\n"
            "from pkg import good\n"
            "takes_two(1)\n"
            "takes_two(1, 2, 3)\n"
            "takes_two(1, 2, zz=9)\n"
            "x = good.nothing_here\n"
            # A keyword hitting an OPTIONAL positional must not mask the
            # missing required one (f(b=2) on f(a, b=1) raises at runtime).
            "def opt(a, b=1):\n    return a\n"
            "opt(b=2)\n"
            # A parameter shadowing a module function must NOT be
            # arity-checked against the module function.
            "def uses(takes_two):\n    return takes_two(1, 2, 3, 4)\n"
        )
        sub = pkg / "sub"
        sub.mkdir()
        (sub / "leaf.py").write_text("def leaf_fn(x):\n    return x\n")
        # Relative import from a nested-package __init__: level 1 is the
        # package itself, and a bad name must be flagged there too.
        (sub / "__init__.py").write_text(
            "from .leaf import leaf_fn, leaf_missing\n"
        )
        monkeypatch.setattr(astlint, "REPO", tmp_path)
        findings: list[str] = []
        files = sorted(pkg.rglob("*.py"))
        index = {
            astlint._modname_for(f): astlint._collect_module(
                f, astlint._modname_for(f)
            )
            for f in files
        }
        import ast as _ast

        for modname, info in index.items():
            astlint._Checker(info, index, findings).visit(
                _ast.parse(info.path.read_text())
            )
        text = "\n".join(findings)
        assert "'absent' is not defined" in text
        assert "missing required args" in text
        assert "takes 2 positional args but 3 given" in text
        assert "unexpected keyword 'zz'" in text
        assert "no attribute 'nothing_here'" in text
        # opt(b=2): the optional-positional keyword can't stand in for
        # the missing required 'a'.
        assert "opt() missing required args" in text
        # Shadowed name: no finding may point at the `uses` body.
        assert "takes 2 positional args but 4 given" not in text
        # Nested __init__ relative import resolves to pkg.sub.leaf.
        assert "'leaf_missing' is not defined in pkg.sub.leaf" in text


class TestMutationRun:
    """tools/mutation_run.py — mutant generation invariants (the full
    subprocess sweep runs via `python tools/mutation_run.py`; its score
    is recorded in NOTES.md)."""

    def test_every_site_yields_a_distinct_compiling_mutant(self):
        from tools.mutation_run import enumerate_mutants, make_mutant

        src = (
            "def f(a, b):\n"
            "    if a == b and a > 0:\n"
            "        return a + 1\n"
            "    return not b\n"
            "FLAG = True\n"
            "NAME = 'proto'\n"
        )
        import ast as _ast

        sites = enumerate_mutants(src)
        assert len(sites) >= 7  # ==, and, >, 0, +, 1, not, return, ...
        unparsed_original = _ast.unparse(_ast.parse(src))
        seen = set()
        for i in range(len(sites)):
            mutated, desc = make_mutant(src, i)
            compile(mutated, "<m>", "exec")
            # Same normalized form ⇒ the mutator applied nothing.
            assert mutated != unparsed_original
            seen.add(mutated)
        # Each site produces a unique mutant (collector/mutator aligned).
        assert len(seen) == len(sites)

    def test_docstrings_and_marked_lines_skipped(self):
        from tools.mutation_run import enumerate_mutants

        src = (
            '"""module docstring"""\n'
            "def f():\n"
            '    """doc"""\n'
            '    print("log line", 123)\n'
            "    return None\n"
        )
        # docstrings skipped, print( line skipped, bare return None
        # not a site:
        assert enumerate_mutants(src) == []

    def test_mutants_change_behavior(self):
        from tools.mutation_run import enumerate_mutants, make_mutant

        src = "def f(a):\n    return a == 3\n"
        sites = enumerate_mutants(src)
        outs = set()
        for i in range(len(sites)):
            mutated, _ = make_mutant(src, i)
            ns: dict = {}
            exec(compile(mutated, "<m>", "exec"), ns)
            outs.add((ns["f"](3), ns["f"](4)))
        base = (True, False)
        assert base not in outs  # every mutant diverges on some input
