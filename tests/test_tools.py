"""Repo tooling: harvest analysis (MIN_T recommendation, tuned-env
extraction, bench.py's application of both), the graftlint static-
analysis framework, and the mutation runner's generation invariants."""

import json
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.crossover_report import (  # noqa: E402
    load,
    recommended_env,
    recommended_min_t,
)


def _steps(rows):
    return {r["step"]: r for r in rows}


class TestRecommendedMinT:
    def test_kernel_wins_everywhere(self):
        steps = _steps(
            [
                {"step": f"crossover_T{t}_kernel", "decode_tok_s": 500},
                {"step": f"crossover_T{t}_xla", "decode_tok_s": 400},
            ][i]
            for t in (1280, 4096)
            for i in (0, 1)
        )
        assert recommended_min_t(steps) == 0

    def test_clean_crossover(self):
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 380},
                {"step": "crossover_T1280_xla", "decode_tok_s": 490},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 400},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
                {"step": "crossover_T8192_kernel", "decode_tok_s": 280},
                {"step": "crossover_T8192_xla", "decode_tok_s": 150},
            ]
        )
        assert recommended_min_t(steps) == 4096

    def test_kernel_never_wins(self):
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 300},
                {"step": "crossover_T1280_xla", "decode_tok_s": 490},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 200},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
            ]
        )
        assert recommended_min_t(steps) == 1 << 31  # kernel off

    def test_mid_loss_resets_suffix(self):
        """kernel wins at 1280, loses at 4096, wins at 8192 → floor is
        8192 (the clean winning suffix), never 1280."""
        steps = _steps(
            [
                {"step": "crossover_T1280_kernel", "decode_tok_s": 500},
                {"step": "crossover_T1280_xla", "decode_tok_s": 400},
                {"step": "crossover_T4096_kernel", "decode_tok_s": 200},
                {"step": "crossover_T4096_xla", "decode_tok_s": 300},
                {"step": "crossover_T8192_kernel", "decode_tok_s": 400},
                {"step": "crossover_T8192_xla", "decode_tok_s": 300},
            ]
        )
        assert recommended_min_t(steps) == 8192

    def test_no_data(self):
        assert recommended_min_t({}) is None


class TestRecommendedEnv:
    def test_sweep_beats_default(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "chunk64", "decode_tok_s": 450},
                {"step": "chunk256", "decode_tok_s": 560},
                {"step": "unroll1", "decode_tok_s": 480},
                {"step": "unroll2", "decode_tok_s": 490},
            ]
        )
        env = recommended_env(steps)
        assert env["ADVSPEC_DECODE_CHUNK"] == "256"
        assert "ADVSPEC_DECODE_UNROLL" not in env  # default 4 won

    def test_defaults_win_yields_no_overrides(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "chunk64", "decode_tok_s": 450},
                {"step": "unroll1", "decode_tok_s": 400},
            ]
        )
        assert recommended_env(steps) == {}

    def test_spec_off_beating_spec_on_sets_kill_switch(self):
        """The comparison uses the PINNED spec_on/spec_off pair, not
        north_star (whose speculation default is governed by the very
        env var being recommended — a north_star baseline would flap)."""
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 560},
                {"step": "spec_on", "decode_tok_s": 500},
                {"step": "spec_off", "decode_tok_s": 550},
            ]
        )
        assert recommended_env(steps)["ADVSPEC_SPECULATIVE"] == "0"

    def test_spec_off_losing_keeps_speculation(self):
        steps = _steps(
            [
                {"step": "north_star", "decode_tok_s": 500},
                {"step": "spec_on", "decode_tok_s": 500},
                {"step": "spec_off", "decode_tok_s": 400},
            ]
        )
        assert "ADVSPEC_SPECULATIVE" not in recommended_env(steps)


class TestBenchAppliesHarvest:
    def test_harvested_tuning_reads_latest_jsonl(self, tmp_path,
                                                 monkeypatch):
        import bench

        rows = [
            {"step": "north_star", "decode_tok_s": 500},
            {"step": "chunk256", "decode_tok_s": 600},
            {"step": "crossover_T1280_kernel", "decode_tok_s": 380},
            {"step": "crossover_T1280_xla", "decode_tok_s": 490},
            {"step": "crossover_T4096_kernel", "decode_tok_s": 400},
            {"step": "crossover_T4096_xla", "decode_tok_s": 300},
        ]
        results = tmp_path / "tpu_results"
        results.mkdir()
        (results / "r04.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows)
        )
        # Point bench at the temp repo layout.
        monkeypatch.setattr(
            bench.os.path, "abspath", lambda p: str(tmp_path / "bench.py")
        )
        env = bench._harvested_tuning()
        assert env["ADVSPEC_DECODE_CHUNK"] == "256"
        assert env["ADVSPEC_PALLAS_MIN_T"] == "4096"

    def test_no_harvest_is_empty(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setattr(
            bench.os.path, "abspath", lambda p: str(tmp_path / "bench.py")
        )
        assert bench._harvested_tuning() == {}

    @pytest.mark.slow
    def test_round_loop_mode_runs(self):
        """The config-4-shaped bench mode produces a complete record
        (driver-facing surface; pinned so the mode can't rot)."""
        import bench

        out = bench._run_round_loop("cpu")
        assert out["rounds"] == 5
        assert out["decode_tokens_total"] == 5 * 4 * 256
        assert out["value"] > 0
        assert out["vs_baseline"] is None  # cpu: no north-star ratio

    def test_load_tolerates_junk_lines(self, tmp_path):
        p = tmp_path / "r.jsonl"
        p.write_text('not json\n{"step": "north_star", '
                     '"decode_tok_s": 1}\n')
        assert load(str(p))["north_star"]["decode_tok_s"] == 1


class TestGraftlint:
    """tools/graftlint — the rule-registry static-analysis framework
    (docs/static_analysis.md). The compat entrypoint tools/astlint.py
    remains the executed typecheck gate."""

    ALL_RULES = {
        "GL-IMPORT",
        "GL-ATTR",
        "GL-ARITY",
        "GL-SYNC",
        "GL-TRACE",
        "GL-RETRACE",
        "GL-REFCOUNT",
        "GL-SUPPRESS",
        "GL-COMMIT",
        "GL-DONATE",
        "GL-ATOMIC",
        "GL-LIFECYCLE",
        "GL-CONFIG",
        "GL-LOCK-GUARD",
        "GL-LOCK-ORDER",
        "GL-LOCK-BLOCKING",
    }

    def test_repo_is_clean(self):
        """The package + tools + tests + entry scripts lint clean under
        EVERY registered rule (the executed typecheck gate, VERDICT r4
        item 5, now with the serving-discipline rules on top) — and no
        grandfathered debt: the committed baseline must be empty."""
        import subprocess

        r = subprocess.run(
            [sys.executable, "-m", "tools.graftlint"],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        # The gate must actually be checking something.
        assert "call sites arity-checked" in r.stderr
        checked = int(r.stderr.rsplit("(", 1)[1].split()[0])
        assert checked > 400
        baseline = json.loads(
            (REPO_ROOT / "tools" / "graftlint" / "baseline.json").read_text()
        )
        assert baseline["entries"] == []

    def test_astlint_compat_entrypoint(self):
        """tools/astlint.py still runs, still exits 0 on the repo, and
        still prints the legacy summary line."""
        import subprocess

        r = subprocess.run(
            [sys.executable, str(REPO_ROOT / "tools" / "astlint.py")],
            capture_output=True,
            text=True,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "astlint: 0 finding(s)" in r.stderr
        assert "call sites arity-checked" in r.stderr

    def test_registry_and_selection(self):
        from tools.graftlint import all_rules, core

        rules = all_rules()
        assert set(rules) == self.ALL_RULES
        for rule in rules.values():
            assert rule.title and rule.rationale and rule.fixtures
        with pytest.raises(KeyError):
            core.run(rules=["GL-NOPE"])

    def test_self_test_every_rule_fires_on_its_fixture(self):
        """The self-test harness proves each registered rule can fail —
        a gate that cannot fail is not a gate."""
        from tools.graftlint import core

        assert core.self_test() == []

    def test_sync_fires_when_allowlist_entry_removed(self):
        """GL-SYNC is doing the exempting: the real batcher DOES
        blanket-sync inside its allowlisted methods, so an emptied
        allowlist must produce findings on them — and the committed
        allowlist none (test_repo_is_clean covers that end to end)."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        src = (
            REPO_ROOT / "adversarial_spec_tpu" / "engine" / "scheduler.py"
        ).read_text()
        findings = lint_sources(
            {"pkg/sched.py": src},
            rules=["GL-SYNC"],
            cfg=GraftlintConfig(sync_allowlist=[]),
        )
        msgs = [f.message for f in findings]
        assert msgs, "emptied allowlist produced no findings"
        assert any(
            "block_until_ready" in m and "_advance_admission" in m
            for m in msgs
        )
        assert any(
            "block_until_ready" in m and "_drive_legacy" in m for m in msgs
        )

    def test_sync_fires_when_any_suppression_removed(self):
        """Acceptance pin: every inline GL-SYNC suppression in
        scheduler.py is load-bearing — removing any ONE of them makes
        the rule fire on exactly that site (none is decorative)."""
        from tools.graftlint.core import lint_sources

        path = (
            REPO_ROOT / "adversarial_spec_tpu" / "engine" / "scheduler.py"
        )
        lines = path.read_text().splitlines(keepends=True)
        supp = [
            i
            for i, line in enumerate(lines)
            if "# graftlint: disable=GL-SYNC" in line
        ]
        assert len(supp) >= 8, "scheduler lost its sanctioned-site map"
        # Fully suppressed as committed:
        assert (
            lint_sources({"pkg/sched.py": "".join(lines)}, rules=["GL-SYNC"])
            == []
        )
        for i in supp:
            mutated = "".join(
                line for j, line in enumerate(lines) if j != i
            )
            findings = lint_sources(
                {"pkg/sched.py": mutated}, rules=["GL-SYNC"]
            )
            assert findings, (
                f"removing the suppression on line {i + 1} produced no "
                "GL-SYNC finding — dead suppression"
            )

    def test_refcount_fires_on_acquire_without_release(self):
        """Acceptance pin: an acquire that can leak on a raise path is a
        finding; the guarded idiom and ownership-transfer-with-finally
        are not."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(refcount_modules=["pkg.alloc_user"])
        leaky = (
            "def admit(alloc, seq, tokens):\n"
            "    alloc.new_sequence(seq)\n"
            "    alloc.extend(seq, len(tokens))  # can raise: leaks seq\n"
            "    return seq\n"
            "\n"
            "def admit_guarded(alloc, seq, tokens):\n"
            "    alloc.new_sequence(seq)\n"
            "    try:\n"
            "        alloc.extend(seq, len(tokens))\n"
            "    except Exception:\n"
            "        alloc.free_sequence(seq)\n"
            "        raise\n"
            "    return seq\n"
            "\n"
            "def share(alloc, seq, pages, n):\n"
            "    try:\n"
            "        alloc.adopt(seq, pages, n)\n"
            "    finally:\n"
            "        alloc.free_sequence(seq)\n"
        )
        findings = lint_sources(
            {"pkg/alloc_user.py": leaky}, rules=["GL-REFCOUNT"], cfg=cfg
        )
        assert len(findings) == 1
        assert findings[0].line == 2
        assert "new_sequence() in admit" in findings[0].message

    def test_refcount_unrelated_guard_is_no_protection(self):
        """An acquire is protected only by its OWN guard — inside the
        try body, or the try opening as the immediately next statement.
        A later sibling guard (for a different sequence) leaves a leak
        window and must not mask the finding."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(refcount_modules=["pkg.m"])
        src = (
            "def f(alloc, a, b, n):\n"
            "    alloc.new_sequence(a)\n"
            "    alloc.extend(a, n)  # raise here leaks a\n"
            "    alloc.new_sequence(b)\n"
            "    try:\n"
            "        alloc.extend(b, n)\n"
            "    except Exception:\n"
            "        alloc.free_sequence(b)\n"
            "        raise\n"
        )
        findings = lint_sources(
            {"pkg/m.py": src}, rules=["GL-REFCOUNT"], cfg=cfg
        )
        assert [f.line for f in findings] == [2]

    def test_refcount_compound_statement_leak_window(self):
        """An acquire nested in a compound statement is protected by
        the compound's next-sibling guard ONLY in tail position: a
        risky statement after the acquire inside the compound is a leak
        window, and a loop body is never tail (later iterations
        intervene)."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(refcount_modules=["pkg.m"])
        guard = (
            "    try:\n"
            "        alloc.extend(seq, 1)\n"
            "    except Exception:\n"
            "        alloc.free_sequence(seq)\n"
            "        raise\n"
        )
        risky = (
            "def f(alloc, seq, tokens):\n"
            "    if tokens:\n"
            "        alloc.new_sequence(seq)\n"
            "        do_risky(tokens)\n" + guard
        )
        findings = lint_sources(
            {"pkg/m.py": risky}, rules=["GL-REFCOUNT"], cfg=cfg
        )
        assert [f.line for f in findings] == [3]
        tail = (
            "def f(alloc, seq, tokens):\n"
            "    if tokens:\n"
            "        alloc.new_sequence(seq)\n" + guard
        )
        assert (
            lint_sources({"pkg/m.py": tail}, rules=["GL-REFCOUNT"], cfg=cfg)
            == []
        )
        loop = (
            "def f(alloc, seq, pages):\n"
            "    for p in pages:\n"
            "        alloc.cache_ref(p)\n"
            "    try:\n"
            "        commit()\n"
            "    except Exception:\n"
            "        alloc.cache_unref(p)\n"
            "        raise\n"
        )
        findings = lint_sources(
            {"pkg/m.py": loop}, rules=["GL-REFCOUNT"], cfg=cfg
        )
        assert [f.line for f in findings] == [3]

    def test_syntax_error_names_the_file(self, tmp_path):
        from tools.graftlint import core
        from tools.graftlint.config import GraftlintConfig

        (tmp_path / "broken.py").write_text("def f(:\n")
        with pytest.raises(SyntaxError, match="broken"):
            core.run(
                [str(tmp_path)],
                repo=tmp_path,
                rules=["GL-IMPORT"],
                cfg=GraftlintConfig(),
                baseline=None,
            )

    def test_config_reader_tolerates_toml_comments(self, tmp_path):
        """Inline comments after values and comment lines inside
        multi-line arrays are valid TOML and must parse, not crash."""
        from tools.graftlint.config import read_graftlint_table

        p = tmp_path / "pyproject.toml"
        p.write_text(
            "[tool.graftlint]\n"
            'sync_class = "ContinuousBatcher"  # the batcher\n'
            "sync_allowlist = [\n"
            "    # keep in sync with docs\n"
            '    "_advance_admission",\n'
            '    "_drive_legacy",  # escape hatch\n'
            "]\n"
        )
        table = read_graftlint_table(p)
        assert table["sync_class"] == "ContinuousBatcher"
        assert table["sync_allowlist"] == [
            "_advance_admission",
            "_drive_legacy",
        ]

    def test_retrace_nested_def_does_not_poison_outer_scope(self):
        """A nested function's local assignment must not degrade a
        same-named outer local to 'dynamic' (scopes are separate)."""
        from tools.graftlint.core import lint_sources

        src = (
            "from functools import partial\n"
            "import jax\n"
            "def _impl(x, *, chunk):\n"
            "    return x\n"
            "step = partial(jax.jit, static_argnames=('chunk',))(_impl)\n"
            "def drive(x, ys):\n"
            "    n = 256\n"
            "    def helper(zs):\n"
            "        n = len(zs)\n"
            "        return n\n"
            "    return step(x, chunk=n)\n"
        )
        assert lint_sources({"pkg/c.py": src}, rules=["GL-RETRACE"]) == []

    def test_stale_suppression_is_flagged(self):
        """A reasoned suppression whose finding was fixed is reported
        stale (only when every suppressed rule actually ran)."""
        from tools.graftlint.core import lint_sources

        src = "import os  # graftlint: disable=GL-SYNC -- was needed\n"
        findings = lint_sources(
            {"pkg/x.py": src}, rules=["GL-SYNC", "GL-SUPPRESS"]
        )
        assert any("stale suppression" in f.message for f in findings)
        # A --rule subset that does NOT run the suppressed rule must
        # not call its suppressions stale.
        findings = lint_sources({"pkg/x.py": src}, rules=["GL-SUPPRESS"])
        assert findings == []

    def test_trace_rule_fires_through_the_jit_closure(self):
        """GL-TRACE reaches bodies only *called* from a jit root: the
        impure call sits in a helper, the jit wrapping is on the
        caller (the fused-program pattern)."""
        from tools.graftlint.core import lint_sources

        src = (
            "import time\n"
            "from functools import partial\n"
            "import jax\n"
            "\n"
            "def helper(x):\n"
            "    return x + time.monotonic()\n"
            "\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def step(x, *, n):\n"
            "    return helper(x)\n"
        )
        findings = lint_sources({"pkg/traced.py": src}, rules=["GL-TRACE"])
        assert len(findings) == 1
        assert "time.monotonic" in findings[0].message
        assert "helper" in findings[0].message

    def test_trace_roots_cover_spec_verify_programs(self):
        """GL-TRACE's discovered roots must include the speculative
        verify programs (ISSUE 6): both the standalone and the fused
        draft+verify chunk are jit roots whose transitive bodies the
        rule walks."""
        from pathlib import Path

        from tools.graftlint.config import load_config
        from tools.graftlint.core import (
            DEFAULT_ROOTS,
            Context,
            build_index,
            collect_files,
        )
        from tools.graftlint.rules.trace import traced_functions

        repo = REPO_ROOT
        cfg = load_config(repo)
        files = collect_files([Path(repo) / r for r in DEFAULT_ROOTS])
        index = build_index(
            files, repo, set(cfg.sig_preserving_decorators)
        )
        ctx = Context(repo, cfg, index)
        roots = {
            fn for (mod, fn) in traced_functions(ctx)
            if mod.endswith("engine.scheduler")
        }
        assert "_spec_chunk_impl" in roots
        assert "fused_prefill_spec_chunk" in roots

    def test_retrace_rule_static_and_traced_args(self):
        from tools.graftlint.core import lint_sources

        src = (
            "from functools import partial\n"
            "import jax\n"
            "import jax.numpy as jnp\n"
            "\n"
            "def bucket_length(n):\n"
            "    return max(128, 1 << (n - 1).bit_length())\n"
            "\n"
            "def _impl(x, n, *, chunk):\n"
            "    return x\n"
            "\n"
            "step = partial(jax.jit, static_argnames=('chunk',))(_impl)\n"
            "\n"
            "def drive(x, xs):\n"
            "    step(x, jnp.int32(0), chunk=256)\n"
            "    step(x, jnp.int32(0), chunk=bucket_length(len(xs)))\n"
            "    step(x, jnp.int32(0), chunk=len(xs))\n"
            "    step(x, len(xs), chunk=256)\n"
        )
        findings = lint_sources({"pkg/calls.py": src}, rules=["GL-RETRACE"])
        assert len(findings) == 2
        by_line = {f.line: f.message for f in findings}
        assert "dynamic Python scalar to a static arg" in by_line[16]
        assert "bare host scalar to a traced arg" in by_line[17]

    def test_suppression_requires_reason(self):
        """A reasoned inline disable suppresses; a reasonless one is
        rejected — the underlying finding survives AND the malformed
        suppression is itself a GL-SUPPRESS finding."""
        from tools.graftlint.core import lint_sources

        body = (
            "import jax\n"
            "class ContinuousBatcher:\n"
            "    def hot(self):\n"
            "        jax.block_until_ready(self.active){}\n"
        )
        reasoned = body.format(
            "  # graftlint: disable=GL-SYNC -- test fixture"
        )
        assert (
            lint_sources({"p/s.py": reasoned}, rules=["GL-SYNC"]) == []
        )
        reasonless = body.format("  # graftlint: disable=GL-SYNC")
        findings = lint_sources(
            {"p/s.py": reasonless}, rules=["GL-SYNC", "GL-SUPPRESS"]
        )
        rules = {f.rule for f in findings}
        assert rules == {"GL-SYNC", "GL-SUPPRESS"}
        assert any("missing mandatory reason" in f.message for f in findings)
        # A typo'd rule id is flagged too (a silently disarmed check).
        typod = body.format(
            "  # graftlint: disable=GL-SNC -- reason given"
        )
        findings = lint_sources(
            {"p/s.py": typod}, rules=["GL-SYNC", "GL-SUPPRESS"]
        )
        assert any("unknown rule" in f.message for f in findings)

    def test_baseline_round_trip(self, tmp_path):
        """write_baseline grandfathers current findings; a re-run
        against that baseline is clean; a NEW finding still fires."""
        from tools.graftlint import core
        from tools.graftlint.config import GraftlintConfig

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "base.py").write_text("def real_thing():\n    return 1\n")
        (pkg / "old.py").write_text(
            "from pkg.base import missing_thing\n"
        )
        cfg = GraftlintConfig()
        baseline = tmp_path / "baseline.json"
        first = core.run(
            [str(pkg)], repo=tmp_path, rules=["GL-IMPORT"], cfg=cfg,
            baseline=None,
        )
        assert len(first.findings) == 1
        core.write_baseline(baseline, first.findings)
        second = core.run(
            [str(pkg)], repo=tmp_path, rules=["GL-IMPORT"], cfg=cfg,
            baseline=baseline,
        )
        assert second.findings == []
        assert len(second.baselined) == 1
        # New debt is not grandfathered.
        (pkg / "new.py").write_text("from pkg.base import also_missing\n")
        third = core.run(
            [str(pkg)], repo=tmp_path, rules=["GL-IMPORT"], cfg=cfg,
            baseline=baseline,
        )
        assert len(third.findings) == 1
        assert "also_missing" in third.findings[0].message

    def test_json_schema_stability(self):
        """The --json payload shape is a driver-facing surface: pin it."""
        import subprocess

        r = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.graftlint",
                "--json",
                "--rule",
                "GL-IMPORT",
                str(REPO_ROOT / "tools" / "graftlint"),
            ],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert set(payload) == {
            "version",
            "rules",
            "findings",
            "counts",
            "files",
            "checked_calls",
            "rule_seconds",
            "artifacts",
        }
        assert payload["version"] == 1
        assert payload["rules"] == ["GL-IMPORT"]
        # Rule-emitted artifacts (GL-LOCK-ORDER's lock_order/lock_edges)
        # only appear when their rule is selected.
        assert payload["artifacts"] == {}
        assert set(payload["counts"]) == {
            "total",
            "suppressed",
            "baselined",
            "by_rule",
        }
        # Per-rule wall timing: every selected rule reports a
        # non-negative float (slow passes visible as the set grows).
        assert set(payload["rule_seconds"]) == {"GL-IMPORT"}
        assert payload["rule_seconds"]["GL-IMPORT"] >= 0.0

    def test_detects_seeded_error_classes(self):
        """Every legacy astlint error class fires on a synthetic
        package — proof the ported gate can still fail."""
        from tools.graftlint.core import lint_sources

        sources = {
            "pkg/good.py": "def takes_two(a, b, *, c=0):\n    return a\n",
            "pkg/bad.py": (
                "from pkg.good import takes_two, absent\n"
                "from pkg import good\n"
                "takes_two(1)\n"
                "takes_two(1, 2, 3)\n"
                "takes_two(1, 2, zz=9)\n"
                "x = good.nothing_here\n"
                # A keyword hitting an OPTIONAL positional must not mask
                # the missing required one (f(b=2) on f(a, b=1) raises).
                "def opt(a, b=1):\n    return a\n"
                "opt(b=2)\n"
                # A parameter shadowing a module function must NOT be
                # arity-checked against the module function.
                "def uses(takes_two):\n    return takes_two(1, 2, 3, 4)\n"
            ),
            "pkg/sub/leaf.py": "def leaf_fn(x):\n    return x\n",
            # Relative import from a nested-package __init__: level 1 is
            # the package itself; a bad name must be flagged there too.
            "pkg/sub/__init__.py": (
                "from .leaf import leaf_fn, leaf_missing\n"
            ),
        }
        findings = lint_sources(
            sources, rules=["GL-IMPORT", "GL-ATTR", "GL-ARITY"]
        )
        text = "\n".join(f.message for f in findings)
        assert "'absent' is not defined" in text
        assert "missing required args" in text
        assert "takes 2 positional args but 3 given" in text
        assert "unexpected keyword 'zz'" in text
        assert "no attribute 'nothing_here'" in text
        # opt(b=2): the optional-positional keyword can't stand in for
        # the missing required 'a'.
        assert "opt() missing required args" in text
        # Shadowed name: no finding may point at the `uses` body.
        assert "takes 2 positional args but 4 given" not in text
        # Nested __init__ relative import resolves to pkg.sub.leaf.
        assert "'leaf_missing' is not defined in pkg.sub.leaf" in text

    def test_shadowed_names_one_level_flow(self):
        """Regression for the _shadowed_names fix: the docstring always
        promised params PLUS local assignment/for/with/except targets,
        but the pre-graftlint code only collected params — a local
        rebind then false-positived against the module function."""
        from tools.graftlint.core import lint_sources

        sources = {
            "pkg/good.py": "def takes_two(a, b):\n    return a\n",
            "pkg/bad.py": (
                "from pkg.good import takes_two\n"
                "def make():\n    return None\n"
                # Local ASSIGNMENT rebind: must not be arity-checked.
                "def via_assign():\n"
                "    takes_two = make()\n"
                "    return takes_two(1, 2, 3, 4)\n"
                # for-target rebind.
                "def via_for(xs):\n"
                "    for takes_two in xs:\n"
                "        takes_two(1, 2, 3, 4)\n"
                # with-target rebind.
                "def via_with(cm):\n"
                "    with cm as takes_two:\n"
                "        return takes_two(1, 2, 3, 4)\n"
                # except-target rebind.
                "def via_except():\n"
                "    try:\n"
                "        return takes_two(1, 2)\n"  # real call: checked
                "    except ValueError as takes_two:\n"
                "        return takes_two\n"
                # AFTER the scoped functions, module-level resolution
                # must be restored: this bad call must still be caught.
                "takes_two(1, 2, 3, 4)\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-ARITY"])
        assert len(findings) == 1
        assert findings[0].line > 15, "local rebind was arity-checked"
        assert "takes 2 positional args but 4 given" in findings[0].message

    def test_config_drift_guard_empty(self):
        """THE pyproject-vs-code-defaults drift guard, shared with the
        tools/lint_all.py graftlint-config stage (hoisted there from
        scattered per-check pins): the [tool.graftlint] table and the
        in-code defaults are the same config, field by field."""
        from tools.graftlint.config import config_drift

        assert config_drift(REPO_ROOT) == []

    # One shared parametrized pin for the per-module process-config
    # defaults (interleave / spec / prefix_cache / kvtier / streaming
    # used to each pin their own): the DATACLASS defaults — what a
    # fresh process arms before any CLI/env override — are part of the
    # serving contract (docs/perf.md's default-on claims) and must not
    # drift silently when a module is touched.
    @pytest.mark.parametrize(
        "modname, cls, knob, expected",
        [
            ("engine.interleave", "InterleaveConfig", "enabled", True),
            ("engine.interleave", "InterleaveConfig", "pipeline_depth", 2),
            ("engine.spec", "SpecConfig", "enabled", True),
            ("engine.spec", "SpecConfig", "gamma", 8),
            ("engine.prefix_cache", "PrefixCacheConfig", "enabled", True),
            ("engine.prefix_cache", "PrefixCacheConfig", "max_pages", 0),
            ("engine.kvtier", "TierConfig", "enabled", True),
            ("engine.kvtier", "TierConfig", "store_dir", ""),
            ("engine.streaming", "StreamConfig", "enabled", True),
            ("engine.streaming", "StreamConfig", "early_cancel", True),
        ],
    )
    def test_module_config_default_pins(self, modname, cls, knob, expected):
        import importlib

        mod = importlib.import_module(f"adversarial_spec_tpu.{modname}")
        fresh = getattr(mod, cls)()  # defaults, not the armed instance
        assert getattr(fresh, knob) == expected

    # -- graftlint v2: interprocedural dataflow + new rule families ----

    def test_sync_taint_survives_helper_extraction(self):
        """The v2 headline: extracting a batcher fetch into a helper
        (method or same-module function) must not launder device taint
        — and a helper fed only host values must stay clean."""
        from tools.graftlint.core import lint_sources

        sources = {
            "pkg/sched.py": (
                "import numpy as np\n"
                "\n"
                "def fetch_rows(buf):\n"
                "    return np.asarray(buf)\n"
                "\n"
                "class ContinuousBatcher:\n"
                "    def _host_helper(self, counts):\n"
                "        return np.asarray(counts)\n"
                "    def _drive(self):\n"
                "        rows = fetch_rows(self.out_buf)\n"
                "        host = [1, 2, 3]\n"
                "        ok = self._host_helper(host)\n"
                "        return rows, ok\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-SYNC"])
        msgs = [f.render() for f in findings]
        assert any(
            "helper fetch_rows" in m and ":4:" in m for m in msgs
        ), msgs
        # The host-fed helper must NOT fire (conservative at unknown /
        # host provenance).
        assert not any("_host_helper" in m for m in msgs), msgs

    def test_sync_taint_through_summaries_and_locals(self):
        """Derived taint: a method whose return derives from device
        attrs taints its callers' locals; assignment chains keep it."""
        from tools.graftlint.core import lint_sources

        sources = {
            "pkg/sched.py": (
                "import jax.numpy as jnp\n"
                "import numpy as np\n"
                "\n"
                "class ContinuousBatcher:\n"
                "    def _counts(self):\n"
                "        return jnp.stack([self.n_emitted])\n"
                "    def _drive(self):\n"
                "        counts = self._counts()\n"
                "        snapshot = counts\n"
                "        return int(snapshot[0])\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-SYNC"])
        assert len(findings) == 1 and "int() on a device value" in (
            findings[0].message
        ), [f.render() for f in findings]

    def test_commit_rule_flags_uncommitted_creation_only(self):
        """GL-COMMIT: a bare creator reaching a persistent attr or a
        holder keyword (directly or through a local) fires; wrapped
        creations and DERIVED state (.at[].set, zeros_like) stay
        clean."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(
            commit_classes=["Batcher"],
            commit_attrs=["active", "cache"],
            commit_holders=["Admission"],
        )
        sources = {
            "pkg/b.py": (
                "import jax.numpy as jnp\n"
                "\n"
                "def init_cache(n):\n"
                "    return {}\n"
                "\n"
                "class Admission:\n"
                "    cache: dict = None\n"
                "\n"
                "class Batcher:\n"
                "    def __init__(self, B):\n"
                "        self.active = jnp.zeros((B,), bool)\n"
                "        self.other = jnp.zeros((B,))\n"
                "    def _commit(self, x):\n"
                "        return x\n"
                "    def admit(self):\n"
                "        ok = self._commit(init_cache(4))\n"
                "        bad = init_cache(4)\n"
                "        a1 = Admission(cache=ok)\n"
                "        a2 = Admission(cache=bad)\n"
                "        a3 = Admission(cache=init_cache(4))\n"
                "        self.active = self.active.at[0].set(False)\n"
                "        self.active = jnp.zeros_like(self.active)\n"
                "        return a1, a2, a3\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-COMMIT"], cfg=cfg)
        lines = sorted(f.line for f in findings)
        # __init__ self.active (11), a2's local flow (19), a3's direct
        # creator keyword (20) — and nothing else: self.other is not a
        # configured attr, ok is wrapped, derived state is derived.
        assert lines == [11, 19, 20], [f.render() for f in findings]

    def test_commit_rule_is_flow_ordered_on_rebinds(self):
        """Review regression: the local-flow env must be per program
        point, not the function's FINAL bindings — a local rebound
        AFTER a holder use must neither poison an earlier committed
        use (false positive) nor launder an earlier uncommitted one
        (false negative)."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(
            commit_classes=["Batcher"],
            commit_attrs=["cache"],
            commit_holders=["Admission"],
        )
        sources = {
            "pkg/b.py": (
                "def init_cache(n):\n"
                "    return {}\n"
                "\n"
                "class Admission:\n"
                "    cache: dict = None\n"
                "\n"
                "class Batcher:\n"
                "    def _commit(self, x):\n"
                "        return x\n"
                "    def good_then_rebound(self):\n"
                "        c = self._commit(init_cache(4))\n"
                "        a = Admission(cache=c)\n"
                "        c = init_cache(4)\n"
                "        return a, self._commit(c)\n"
                "    def bad_then_laundered(self):\n"
                "        c = init_cache(4)\n"
                "        a = Admission(cache=c)\n"
                "        c = self._commit(init_cache(4))\n"
                "        return a, c\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-COMMIT"], cfg=cfg)
        assert [f.line for f in findings] == [17], [
            f.render() for f in findings
        ]

    def test_donate_rule_escape_positions_and_snapshots(self):
        """GL-DONATE: a raw alias stored in the dispatch loop fires; a
        jnp.copy snapshot, the rebind idiom, a post-loop return, and
        the staged-args splat are all clean."""
        from tools.graftlint.core import lint_sources

        src = (
            "from functools import partial\n"
            "import jax\n"
            "import jax.numpy as jnp\n"
            "\n"
            "def _impl(pool, out_buf):\n"
            "    return pool, out_buf\n"
            "\n"
            "step = partial(jax.jit, donate_argnames=('pool', 'out_buf'))"
            "(_impl)\n"
            "\n"
            "def drive(pool, out_buf, n):\n"
            "    entries = []\n"
            "    for _ in range(n):\n"
            "        entries.append((out_buf,))\n"
            "        snap = (jnp.copy(out_buf),)\n"
            "        pool, out_buf = step(pool, out_buf)\n"
            "        args = (pool, out_buf)\n"
            "        pool, out_buf = step(*args)\n"
            "    return out_buf\n"
        )
        findings = lint_sources({"pkg/d.py": src}, rules=["GL-DONATE"])
        assert [f.line for f in findings] == [13], [
            f.render() for f in findings
        ]
        assert "container literal" in findings[0].message

    def test_donate_rule_interprocedural_method_summary(self):
        """A method that donates self.X marks ITS callers' escapes: the
        PR 9 shape — dispatch in one method, raw alias stored in the
        drive loop of another."""
        from tools.graftlint.core import lint_sources

        src = (
            "from functools import partial\n"
            "import jax\n"
            "\n"
            "def _impl(out_buf):\n"
            "    return out_buf\n"
            "\n"
            "step = partial(jax.jit, donate_argnames=('out_buf',))(_impl)\n"
            "\n"
            "class Batcher:\n"
            "    def _dispatch(self):\n"
            "        self.out_buf = step(self.out_buf)\n"
            "    def _drive(self, n):\n"
            "        inflight = []\n"
            "        while n:\n"
            "            self._dispatch()\n"
            "            inflight.append((self.out_buf,))\n"
            "            n -= 1\n"
            "        return inflight\n"
        )
        findings = lint_sources({"pkg/d.py": src}, rules=["GL-DONATE"])
        assert [f.line for f in findings] == [16], [
            f.render() for f in findings
        ]
        assert "self.out_buf" in findings[0].message

    def test_atomic_rule_scope_and_allowlist(self):
        """GL-ATOMIC: write-mode opens / write_text inside the package
        fire unless the enclosing function is a sanctioned
        implementation; reads and out-of-package writes are free."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(
            package="pkg", atomic_funcs=["pkg.io:atomic_write"]
        )
        sources = {
            "pkg/io.py": (
                "import os\n"
                "\n"
                "def atomic_write(path, data):\n"
                "    with open(path + '.tmp', 'w') as f:\n"
                "        f.write(data)\n"
                "    os.replace(path + '.tmp', path)\n"
                "\n"
                "def torn_write(path, data):\n"
                "    with open(path, 'w') as f:\n"
                "        f.write(data)\n"
                "\n"
                "def reader(path):\n"
                "    return open(path).read()\n"
            ),
            "elsewhere/scratch.py": (
                "def dump(path, data):\n"
                "    open(path, 'w').write(data)\n"
            ),
        }
        findings = lint_sources(sources, rules=["GL-ATOMIC"], cfg=cfg)
        assert [f.line for f in findings] == [9], [
            f.render() for f in findings
        ]
        assert "torn_write" in findings[0].message

    def test_lifecycle_rule_exit_reachability_and_side_writes(self):
        """GL-LIFECYCLE: an exit path that never reaches the shared
        surgery fires, a hand-rolled ownership write outside the
        surgery fires, and the sanctioned paths stay clean."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg = GraftlintConfig(
            lifecycle_class="Batcher",
            lifecycle_release="_release_slot",
            lifecycle_exits=["_finish_slot", "_cancel_slot"],
            lifecycle_owned_attrs=["_slot_req", "_slot_seq"],
            lifecycle_mutators=["_finish_admission"],
        )
        sources = {
            "pkg/sched.py": (
                "class Batcher:\n"
                "    def __init__(self, B):\n"
                "        self._slot_req = [None] * B\n"
                "    def _finish_admission(self, slot, req):\n"
                "        self._slot_req[slot] = req\n"
                "    def _release_slot(self, slot):\n"
                "        self._slot_req[slot] = None\n"
                "        self._slot_seq[slot] = None\n"
                "    def _finish_slot(self, slot):\n"
                "        self._release_slot(slot)\n"
                "    def _cancel_slot(self, slot):\n"
                "        self._slot_req[slot] = None\n"
            ),
        }
        findings = lint_sources(
            sources, rules=["GL-LIFECYCLE"], cfg=cfg
        )
        msgs = [f.render() for f in findings]
        assert len(findings) == 2, msgs
        assert any(
            "never reaches the shared release surgery" in m for m in msgs
        )
        assert any("self._slot_req written" in m for m in msgs)

    def test_config_rule_stale_entries(self):
        """GL-CONFIG (stale-allowlist detection): a table entry that
        matches nothing in the indexed package is a finding; live
        entries are not; a path-subset run proves nothing and skips."""
        from tools.graftlint.config import GraftlintConfig
        from tools.graftlint.core import lint_sources

        cfg_kwargs = dict(
            package="pkg",
            sync_class="Batcher",
            sync_allowlist=["_live", "_ghost"],
            sync_device_attrs=["active"],
            sync_device_names=[],
            refcount_modules=[],
            refcount_pairs=[],
            retrace_bucketers=[],
            commit_classes=[],
            commit_attrs=[],
            commit_holders=[],
            atomic_funcs=[],
            lifecycle_class="Batcher",
            lifecycle_release="_live",
            lifecycle_exits=[],
            lifecycle_owned_attrs=[],
            lifecycle_mutators=[],
            fleet_lifecycle_class="",  # fixture has no fleet machine
            serve_lifecycle_class="",  # fixture has no serve machine
            weightres_lifecycle_class="",  # nor a weight-ledger machine
            autoscale_lifecycle_class="",  # nor an autoscaler machine
            handoff_lifecycle_class="",  # nor a handoff ledger
            lock_guards=[],  # nor any declared locks
            lock_thread_entries=[],
        )
        sources = {
            "pkg/sched.py": (
                "class Batcher:\n"
                "    def _live(self):\n"
                "        return self.active\n"
            ),
        }
        findings = lint_sources(
            sources,
            rules=["GL-CONFIG"],
            cfg=GraftlintConfig(**cfg_kwargs),
        )
        msgs = [f.message for f in findings]
        assert len(findings) == 1, msgs
        assert "'_ghost'" in msgs[0] and "sync_allowlist" in msgs[0]

    def test_changed_mode_filter(self):
        """lint_all's --changed filter keeps only existing .py files
        under the lint roots."""
        from tools.lint_all import lintable

        names = [
            "adversarial_spec_tpu/engine/scheduler.py",
            "tools/lint_all.py",
            "bench.py",
            "docs/static_analysis.md",  # not .py
            "adversarial_spec_tpu/engine/ghost.py",  # doesn't exist
            "somewhere_else/module.py",  # outside the roots
        ]
        assert lintable(names, REPO_ROOT) == [
            "adversarial_spec_tpu/engine/scheduler.py",
            "bench.py",
            "tools/lint_all.py",
        ]

    # -- regression-class pins: the two historical bugs, permanently --

    def _scheduler_src(self):
        return (
            REPO_ROOT / "adversarial_spec_tpu" / "engine" / "scheduler.py"
        ).read_text()

    def test_commit_regression_pin(self):
        """Deleting the ``self._commit`` wrapper (the PR 5/6 double-
        compile bugs, scheduler.py `_commit`) makes GL-COMMIT fire on
        the real codebase — and the committed source is clean."""
        from tools.graftlint.core import lint_sources

        src = self._scheduler_src()
        path = "adversarial_spec_tpu/engine/scheduler.py"
        assert (
            lint_sources({path: src}, rules=["GL-COMMIT"]) == []
        ), "committed scheduler must be GL-COMMIT clean"
        assert "self._commit(" in src
        mutated = src.replace("self._commit(", "(")
        findings = lint_sources({path: mutated}, rules=["GL-COMMIT"])
        assert findings, (
            "removing the _commit wrapper produced no GL-COMMIT "
            "finding — the double-compile class is unguarded"
        )
        # Both historical sites are caught: the admission cache
        # (holder keyword, PR 5) and batcher row state (PR 6).
        msgs = " ".join(f.message for f in findings)
        assert "cache" in msgs and "self." in msgs

    def test_donate_regression_pin(self):
        """Deleting the ``jnp.copy`` snapshot (the PR 9 donated-buffer
        bug, scheduler.py streaming entry) makes GL-DONATE fire on the
        real codebase — and the committed source is clean."""
        from tools.graftlint.core import lint_sources

        src = self._scheduler_src()
        path = "adversarial_spec_tpu/engine/scheduler.py"
        assert (
            lint_sources({path: src}, rules=["GL-DONATE"]) == []
        ), "committed scheduler must be GL-DONATE clean"
        needle = "jnp.copy(self.out_buf) if streaming else None"
        assert needle in src
        mutated = src.replace(needle, "self.out_buf if streaming else None")
        findings = lint_sources({path: mutated}, rules=["GL-DONATE"])
        assert findings, (
            "removing the jnp.copy snapshot produced no GL-DONATE "
            "finding — the use-after-donate class is unguarded"
        )
        assert any("self.out_buf" in f.message for f in findings)


class TestObsDump:
    """tools/obs_dump.py — offline validator/pretty-printer for flight-
    recorder JSONL (the triage half of the observability subsystem)."""

    def _dump(self, tmp_path, events):
        import json

        p = tmp_path / "ev.jsonl"
        p.write_text(
            "".join(json.dumps(e) + "\n" for e in events), encoding="utf-8"
        )
        return str(p)

    def _recorder_dump(self, tmp_path):
        from adversarial_spec_tpu.obs import (
            FaultEvent,
            FlightRecorder,
            RequestEvent,
            StepEvent,
        )

        r = FlightRecorder(size=64)
        r.append(RequestEvent(req_id=0, state="queued", tokens=8))
        r.append(RequestEvent(req_id=0, state="admitted", slot=1, tokens=8))
        r.append(
            StepEvent(kind="fused", n_live=2, admission_slot=1,
                      prefill_tokens=64, decode_chunk=4, pipeline_depth=2)
        )
        r.append(StepEvent(kind="decode", n_live=2, decode_chunk=4,
                           sync_reason="depth_fetch"))
        r.append(
            FaultEvent(seam="scheduler_chunk", kind="oom", slot=1,
                       req_id=0, pages_freed=3)
        )
        r.append(RequestEvent(req_id=0, state="evicted", slot=1))
        p = tmp_path / "real.jsonl"
        r.dump_jsonl(str(p))
        return str(p)

    def test_real_recorder_dump_validates_exit_0(self, tmp_path, capsys):
        from tools.obs_dump import main

        path = self._recorder_dump(tmp_path)
        assert main([path, "--timeline", "--requests"]) == 0
        out = capsys.readouterr().out
        assert "6 event(s)" in out
        assert "oom at scheduler_chunk" in out
        assert "3 page(s) freed" in out

    def test_occupancy_timeline_renders_bars_and_annotations(
        self, tmp_path, capsys
    ):
        from tools.obs_dump import load_events, occupancy_timeline

        events, errors = load_events(self._recorder_dump(tmp_path))
        assert errors == []
        text = occupancy_timeline(events)
        assert "#" in text  # fused glyph at full occupancy
        assert "adm@1+64tok" in text
        assert "depth=2" in text
        assert "sync=depth_fetch" in text

    def test_schema_violations_exit_1_and_are_listed(self, tmp_path, capsys):
        from tools.obs_dump import main

        path = self._dump(
            tmp_path,
            [
                {"seq": 1, "type": "nope"},
                {"seq": 2, "type": "request", "req_id": "zero",
                 "state": "queued", "slot": -1, "tokens": 0,
                 "cached_tokens": 0},
                {"seq": 3, "type": "step", "kind": "decode", "n_live": 0,
                 "admission_slot": -1, "prefill_tokens": 0,
                 "decode_chunk": 0, "pipeline_depth": 0,
                 "sync_reason": ""},
            ],
        )
        assert main([path]) == 1
        err = capsys.readouterr().err
        assert "unknown event type 'nope'" in err
        assert "req_id" in err
        assert "schema violation" in err

    def test_unreadable_input_exits_2(self, tmp_path):
        from tools.obs_dump import main

        assert main([str(tmp_path / "missing.jsonl")]) == 2

    def test_unexpected_recompiles_warn_in_summary(self, tmp_path, capsys):
        from tools.obs_dump import main

        path = self._dump(
            tmp_path,
            [
                {"seq": 1, "type": "compile", "program": "decode",
                 "key": "(4,)", "n_compiles": 2, "unexpected": True,
                 "trace_id": "", "span_id": ""},
            ],
        )
        assert main([path]) == 0
        assert "unexpected jit recompile" in capsys.readouterr().out

    def test_schemas_track_the_dataclasses(self):
        """EVENT_FIELDS derives from the dataclasses — a new event field
        is validated automatically, never silently ignored."""
        import dataclasses

        from adversarial_spec_tpu.obs import EVENT_FIELDS
        from adversarial_spec_tpu.obs.events import EVENT_TYPES

        for cls in EVENT_TYPES:
            assert set(EVENT_FIELDS[cls.TYPE]) == {
                f.name for f in dataclasses.fields(cls)
            }
        # Trace ids are part of EVERY event's schema, and the span
        # event is in the vocabulary.
        for cls in EVENT_TYPES:
            assert "trace_id" in EVENT_FIELDS[cls.TYPE]
            assert "span_id" in EVENT_FIELDS[cls.TYPE]
        assert "span" in EVENT_FIELDS

    def _traced_dump(self, tmp_path):
        from adversarial_spec_tpu.obs import (
            FlightRecorder,
            RequestEvent,
            SpanEvent,
            StepEvent,
        )

        r = FlightRecorder(size=64)
        r.append(
            SpanEvent(name="request", phase="begin", req_id=0,
                      trace_id="tr-001-01", span_id="tr-001-01/s00")
        )
        r.append(
            RequestEvent(req_id=0, state="queued", tokens=8,
                         trace_id="tr-001-01", span_id="tr-001-01/s00")
        )
        r.append(
            StepEvent(kind="decode", n_live=1, decode_chunk=4,
                      trace_id="tr-001-01")
        )
        r.append(
            SpanEvent(name="prefill", phase="end", req_id=0, slot=1,
                      wall_s=0.25, trace_id="tr-001-01",
                      span_id="tr-001-01/s00")
        )
        r.append(
            StepEvent(kind="decode", n_live=1, decode_chunk=4,
                      trace_id="tr-002-01")
        )
        p = tmp_path / "traced.jsonl"
        r.dump_jsonl(str(p))
        return str(p)

    def test_trace_filter_scopes_the_views(self, tmp_path, capsys):
        from tools.obs_dump import main

        path = self._traced_dump(tmp_path)
        assert main([path, "--trace", "tr-001-01"]) == 0
        out = capsys.readouterr().out
        assert "4 event(s)" in out  # the tr-002-01 step is filtered
        assert main([path, "--trace", "tr-002-01"]) == 0
        assert "1 event(s)" in capsys.readouterr().out

    def test_span_rows_render_in_timeline_and_request_log(
        self, tmp_path, capsys
    ):
        from tools.obs_dump import main

        path = self._traced_dump(tmp_path)
        assert main([path, "--timeline", "--requests"]) == 0
        out = capsys.readouterr().out
        assert "request:begin" in out
        assert "prefill:end" in out
        assert "0.2500s" in out  # end rows carry the stage wall
        assert "span begin" in out  # legend documents the glyphs
        assert "span=tr-001-01/s00" in out  # request log row suffix


class TestTraceView:
    """tools/trace_view.py — per-request waterfalls + the CHECKED
    stage-wall decomposition (deeper coverage incl. corruption rides
    tests/test_trace.py with real scheduler/mock streams)."""

    def _write(self, tmp_path, events):
        import json

        p = tmp_path / "ev.jsonl"
        p.write_text(
            "".join(json.dumps(e) + "\n" for e in events),
            encoding="utf-8",
        )
        return str(p)

    def _span(self, seq, name, phase, wall=0.0, sid="tr-001-01/s00"):
        return {
            "seq": seq, "type": "span", "name": name, "phase": phase,
            "req_id": 0, "slot": 0, "wall_s": wall,
            "trace_id": "tr-001-01", "span_id": sid,
        }

    def test_consistent_stream_renders_and_exits_0(self, tmp_path, capsys):
        from tools.trace_view import main

        path = self._write(
            tmp_path,
            [
                self._span(1, "request", "begin"),
                self._span(2, "queued", "end", 0.01),
                self._span(3, "prefill", "end", 0.25),
                self._span(4, "decode", "end", 0.75),
                self._span(5, "request", "end", 1.0),
            ],
        )
        assert main([path]) == 0
        out = capsys.readouterr().out
        assert "service 1.0000s" in out
        assert "critical path: tr-001-01/s00" in out
        assert "dominant stage: decode" in out

    def test_sum_violation_exits_1(self, tmp_path, capsys):
        from tools.trace_view import main

        path = self._write(
            tmp_path,
            [
                self._span(1, "prefill", "end", 0.25),
                self._span(2, "decode", "end", 0.25),
                self._span(3, "request", "end", 1.0),
            ],
        )
        assert main([path]) == 1
        assert "DECOMPOSITION VIOLATION" in capsys.readouterr().err

    def test_open_requests_are_rendered_not_checked(self, tmp_path):
        """A request evicted mid-flight (no decode end) waterfalls as
        'open' but cannot fail the sum check — there is nothing to
        check yet."""
        from tools.trace_view import main

        path = self._write(
            tmp_path,
            [
                self._span(1, "request", "begin"),
                self._span(2, "prefill", "end", 0.25),
            ],
        )
        assert main([path]) == 0

    def test_trace_scoping_and_json_mode(self, tmp_path, capsys):
        import json as json_mod

        from tools.trace_view import main

        path = self._write(
            tmp_path,
            [
                self._span(1, "prefill", "end", 0.5),
                self._span(2, "decode", "end", 0.5),
                self._span(3, "request", "end", 1.0),
                self._span(
                    4, "request", "end", 9.0, sid="tr-002-01/s00"
                )
                | {"trace_id": "tr-002-01"},
            ],
        )
        assert main([path, "--trace", "tr-001-01", "--json"]) == 0
        data = json_mod.loads(capsys.readouterr().out)
        assert set(data["requests"]) == {"tr-001-01/s00"}
        assert data["check_problems"] == []

    def test_unreadable_input_exits_2(self, tmp_path):
        from tools.trace_view import main

        assert main([str(tmp_path / "missing.jsonl")]) == 2


class TestBenchTrend:
    """tools/bench_trend.py — the BENCH_*.json join + schema gate."""

    def _metric_file(self, tmp_path, name="BENCH_demo.json", **over):
        import json

        payload = {
            "metric": "demo_metric", "value": 1.5, "unit": "x",
            "platform": "cpu", "within_budget": True,
        }
        payload.update(over)
        for k, v in list(payload.items()):
            if v is None:
                del payload[k]
        (tmp_path / name).write_text(json.dumps(payload))
        return payload

    def test_joins_metric_and_ladder_files(self, tmp_path, capsys):
        import json

        from tools.bench_trend import main

        self._metric_file(tmp_path)
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(
                {
                    "n": 1, "cmd": "python bench.py", "rc": 0,
                    "tail": "…",
                    "parsed": {
                        "metric": "tok_per_sec", "value": 497.9,
                        "unit": "tok/s", "platform": "tpu",
                    },
                }
            )
        )
        assert main(["--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "demo_metric" in out and "tok_per_sec" in out
        assert "497.9" in out
        assert main(["--dir", str(tmp_path), "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert [r["mode"] for r in data["rows"]] == ["demo", "r01"]
        assert data["problems"] == []

    def test_schema_violation_fails_the_gate(self, tmp_path, capsys):
        from tools.bench_trend import main

        self._metric_file(
            tmp_path, name="BENCH_bad.json", value="fast"
        )
        assert main(["--dir", str(tmp_path)]) == 1
        err = capsys.readouterr().err
        assert "BENCH_bad.json" in err and "value" in err

    def test_successful_ladder_run_requires_parsed_payload(
        self, tmp_path, capsys
    ):
        import json

        from tools.bench_trend import main

        (tmp_path / "BENCH_r09.json").write_text(
            json.dumps({"n": 9, "cmd": "x", "rc": 0, "tail": ""})
        )
        assert main(["--dir", str(tmp_path)]) == 1
        assert "no parsed metric payload" in capsys.readouterr().err
        # A FAILED ladder run legitimately has no payload.
        (tmp_path / "BENCH_r09.json").write_text(
            json.dumps({"n": 9, "cmd": "x", "rc": 1, "tail": "boom"})
        )
        assert main(["--dir", str(tmp_path)]) == 0
        capsys.readouterr()
        # ...but a parsed payload PRESENT on a failed run must still
        # schema-validate: malformed fields are a gate failure, not a
        # crash in the renderer.
        (tmp_path / "BENCH_r09.json").write_text(
            json.dumps(
                {
                    "n": 9, "cmd": "x", "rc": 1, "tail": "boom",
                    "parsed": {
                        "metric": "m", "value": "fast", "unit": "x",
                        "platform": "cpu",
                    },
                }
            )
        )
        assert main(["--dir", str(tmp_path)]) == 1
        assert "value" in capsys.readouterr().err

    def test_committed_bench_record_is_valid(self):
        """The repo's own BENCH_* files pass the gate (this is what
        lint_all --full runs)."""
        from pathlib import Path

        from tools.bench_trend import collect

        rows, problems = collect(Path(__file__).resolve().parent.parent)
        assert problems == []
        assert len(rows) >= 8
        modes = {r["mode"] for r in rows}
        assert {"obs", "prefix", "spec", "tier", "interleave"} <= modes
        obs_row = next(r for r in rows if r["mode"] == "obs")
        assert obs_row["within_budget"] is True

    def test_empty_and_missing_dirs_exit_2(self, tmp_path):
        from tools.bench_trend import main

        assert main(["--dir", str(tmp_path)]) == 2
        assert main(["--dir", str(tmp_path / "nope")]) == 2


class TestMutationRun:
    """tools/mutation_run.py — mutant generation invariants (the full
    subprocess sweep runs via `python tools/mutation_run.py`; its score
    is recorded in NOTES.md)."""

    def test_every_site_yields_a_distinct_compiling_mutant(self):
        from tools.mutation_run import enumerate_mutants, make_mutant

        src = (
            "def f(a, b):\n"
            "    if a == b and a > 0:\n"
            "        return a + 1\n"
            "    return not b\n"
            "FLAG = True\n"
            "NAME = 'proto'\n"
        )
        import ast as _ast

        sites = enumerate_mutants(src)
        assert len(sites) >= 7  # ==, and, >, 0, +, 1, not, return, ...
        unparsed_original = _ast.unparse(_ast.parse(src))
        seen = set()
        for i in range(len(sites)):
            mutated, desc = make_mutant(src, i)
            compile(mutated, "<m>", "exec")
            # Same normalized form ⇒ the mutator applied nothing.
            assert mutated != unparsed_original
            seen.add(mutated)
        # Each site produces a unique mutant (collector/mutator aligned).
        assert len(seen) == len(sites)

    def test_docstrings_and_marked_lines_skipped(self):
        from tools.mutation_run import enumerate_mutants

        src = (
            '"""module docstring"""\n'
            "def f():\n"
            '    """doc"""\n'
            '    print("log line", 123)\n'
            "    return None\n"
        )
        # docstrings skipped, print( line skipped, bare return None
        # not a site:
        assert enumerate_mutants(src) == []

    def test_mutants_change_behavior(self):
        from tools.mutation_run import enumerate_mutants, make_mutant

        src = "def f(a):\n    return a == 3\n"
        sites = enumerate_mutants(src)
        outs = set()
        for i in range(len(sites)):
            mutated, _ = make_mutant(src, i)
            ns: dict = {}
            exec(compile(mutated, "<m>", "exec"), ns)
            outs.add((ns["f"](3), ns["f"](4)))
        base = (True, False)
        assert base not in outs  # every mutant diverges on some input
