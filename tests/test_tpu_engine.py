"""TpuEngine integration tests on CPU with synthetic checkpoints — the
whole tpu:// path (registry → loader → mesh → batched generate → detokenize)
without TPUs or downloads (SURVEY §4: fake-at-the-seam, real everything
else; here even the engine is real, only the hardware is swapped)."""

import pytest

from adversarial_spec_tpu.cli import main as cli_main
from adversarial_spec_tpu.engine.registry import (
    ModelSpec,
    save_registry_entry,
)
from adversarial_spec_tpu.engine.tpu import (
    TpuEngine,
    hbm_budget_bytes,
    per_chip_param_bytes,
)
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

PARAMS = SamplingParams(max_new_tokens=8, greedy=True)


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """This module pins the engine seam (registry → loader → mesh →
    serve); speculation is default-on and only multiplies the jit
    programs every engine here compiles. The engine × speculation
    interaction is pinned by test_paged_spec_uses_batcher_and_matches_dense
    (which opts back in) and tests/test_spec_batcher.py."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


def _req(model, user="hello"):
    return ChatRequest(model=model, system="sys", user=user)


@pytest.fixture(scope="module")
def engine():
    return TpuEngine()


class TestTpuEngine:
    def test_single_request(self, engine):
        comp = engine.chat([_req("tpu://random-tiny")], PARAMS)[0]
        assert comp.ok, comp.error
        assert comp.usage.output_tokens > 0
        assert comp.usage.input_tokens > 0
        assert comp.usage.decode_tokens == comp.usage.output_tokens

    def test_batched_same_model(self, engine):
        comps = engine.chat(
            [_req("tpu://random-tiny", "a"), _req("tpu://random-tiny", "bb")],
            PARAMS,
        )
        assert len(comps) == 2
        assert all(c.ok for c in comps)

    def test_greedy_batch_matches_single(self, engine):
        """Batching must not change a row's greedy output (left-pad
        correctness through the full engine stack)."""
        single = engine.chat([_req("tpu://random-tiny", "xyz")], PARAMS)[0]
        batch = engine.chat(
            [
                _req("tpu://random-tiny", "xyz"),
                _req("tpu://random-tiny", "a completely different prompt"),
            ],
            PARAMS,
        )
        assert batch[0].text == single.text

    def test_heterogeneous_pool_sequential_groups(self, engine):
        comps = engine.chat(
            [
                _req("tpu://random-tiny"),
                _req("tpu://random-mistral-tiny"),
                _req("tpu://random-tiny"),
            ],
            PARAMS,
        )
        assert len(comps) == 3
        assert all(c.ok for c in comps), [c.error for c in comps]

    def test_unknown_alias_degrades_to_error(self, engine):
        comp = engine.chat([_req("tpu://nope")], PARAMS)[0]
        assert not comp.ok
        assert "unknown tpu model alias" in comp.error

    def test_byte_budget_evicts_lru(self, monkeypatch):
        """Residency is HBM-byte-budgeted: with a budget sized for ~1.5
        tiny models, loading a second model evicts the first (LRU), and
        the resident set's bytes stay within budget."""
        eng = TpuEngine()
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        one = eng._models["random-tiny"].bytes_per_chip
        assert one > 0
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        eng.chat([_req("tpu://random-mistral-tiny")], PARAMS)
        assert "random-mistral-tiny" in eng._models
        assert "random-tiny" not in eng._models
        resident = sum(m.bytes_per_chip for m in eng._models.values())
        assert resident <= hbm_budget_bytes()

    def test_two_model_round_within_budget_stays_resident(self, engine):
        """Two tiny models fit the default budget together, so a
        heterogeneous round keeps BOTH resident — repeat rounds swap
        nothing (the mix-families debate setup)."""
        engine.chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert {"random-tiny", "random-mistral-tiny"} <= set(
            engine._models
        )
        resident = sum(
            m.bytes_per_chip for m in engine._models.values()
        )
        assert resident <= hbm_budget_bytes()

    def test_heterogeneous_round_prefetches_next_group(self):
        """The second group's weights load on the background thread
        while the first group decodes (swap/compute overlap)."""
        eng = TpuEngine()
        comps = eng.chat(
            [
                _req("tpu://random-tiny"),
                _req("tpu://random-mistral-tiny"),
            ],
            PARAMS,
        )
        assert all(c.ok for c in comps)
        assert eng.prefetch_hits >= 1

    def test_per_chip_param_bytes_counts_shards(self):
        """Sharded leaves count one device's shard, replicated leaves the
        whole array."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from adversarial_spec_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        mesh = make_mesh({"tp": 2})
        x = jax.device_put(
            jnp.zeros((4, 8), jnp.float32),
            NamedSharding(mesh, P(None, "tp")),
        )
        r = jax.device_put(
            jnp.zeros((4,), jnp.float32), NamedSharding(mesh, P())
        )
        assert per_chip_param_bytes({"x": x, "r": r}) == 4 * 4 * 4 + 16

    def test_validate(self, engine):
        assert engine.validate("tpu://random-tiny") is None
        assert engine.validate("tpu://missing") is not None

    def test_registry_entry_with_bad_checkpoint_errors(self, engine):
        save_registry_entry(
            ModelSpec(alias="broken", checkpoint="/not/a/dir")
        )
        comp = engine.chat([_req("tpu://broken")], PARAMS)[0]
        assert not comp.ok


class TestCliTpuPath:
    def test_critique_with_tpu_model(self, monkeypatch, capsys):
        import io, json

        monkeypatch.setattr(
            "sys.stdin", io.StringIO("# Spec\nshort body")
        )
        code = cli_main(
            [
                "critique",
                "--models",
                "tpu://random-tiny",
                "--max-new-tokens",
                "8",
                "--greedy",
                "--json",
            ]
        )
        out, err = capsys.readouterr()
        assert code == 0, err
        data = json.loads(out)
        r = data["results"][0]
        assert r["error"] is None
        assert r["output_tokens"] > 0
        assert data["cost"]["models"]["tpu://random-tiny"]["cost_usd"] == 0.0


class TestPerRowUsageAttribution:
    def test_early_eos_row_billed_less(self, engine, monkeypatch):
        """VERDICT r1 item 8: device time attributes proportionally to
        per-row decode counts — an early-EOS row must report less device
        and decode time than a full-budget row, and the row sums must
        reproduce the call totals."""
        import numpy as np

        from adversarial_spec_tpu.engine import tpu as tpu_mod
        from adversarial_spec_tpu.engine.generate import GenerateResult

        def fake_generate(params, cfg, prompts, **kw):
            B = len(prompts)
            toks = np.zeros((B, 8), np.int32)
            toks[:, :] = 5
            return GenerateResult(
                tokens=toks,
                n_generated=np.array([2, 8][:B], np.int64),
                prefill_time_s=0.5,
                decode_time_s=1.0,
                decode_tokens=10,
            )

        monkeypatch.setattr(tpu_mod, "generate", fake_generate)
        comps = engine.chat(
            [_req("tpu://random-tiny", "a"), _req("tpu://random-tiny", "b")],
            PARAMS,
        )
        short, full = comps
        assert short.usage.output_tokens == 2
        assert full.usage.output_tokens == 8
        # Proportional decode attribution: 2/10 vs 8/10 of 1.0 s.
        assert abs(short.usage.decode_time_s - 0.2) < 1e-9
        assert abs(full.usage.decode_time_s - 0.8) < 1e-9
        assert short.usage.device_time_s < full.usage.device_time_s
        # Sums reproduce the totals (decode exactly; device time includes
        # the evenly split prefill/overhead remainder).
        assert abs(
            short.usage.decode_time_s + full.usage.decode_time_s - 1.0
        ) < 1e-9


class TestContinuousServing:
    """Paged single-device specs route through the ContinuousBatcher
    (NOTES round-2: 'ContinuousBatcher exists and is tested but is not
    wired into the engine')."""

    def test_paged_spec_uses_batcher_and_matches_dense(self, engine):
        import adversarial_spec_tpu.engine.tpu as tpu_mod
        from adversarial_spec_tpu.engine import spec as spec_mod

        # Opt back in (module _spec_off fixture): this test IS the
        # engine × speculation pin — the batcher must speculate and
        # still match the dense engine's greedy tokens.
        spec_mod.configure(enabled=True)
        save_registry_entry(
            ModelSpec(alias="cont-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        save_registry_entry(
            ModelSpec(alias="dense-tiny", family="llama", size="tiny",
                      dtype="float32")
        )
        calls = []
        orig = tpu_mod.TpuEngine._chat_continuous

        def spy(self, lm, prompts, params, batch=None, consumer=None):
            calls.append(len(prompts))
            return orig(self, lm, prompts, params, batch, consumer)

        tpu_mod.TpuEngine._chat_continuous = spy
        try:
            reqs = [
                _req("tpu://cont-tiny", "alpha beta"),
                _req("tpu://cont-tiny", "gamma"),
                _req("tpu://cont-tiny", "a longer third prompt here"),
            ]
            comps = engine.chat(reqs, PARAMS)
        finally:
            tpu_mod.TpuEngine._chat_continuous = orig
        assert calls == [3], "paged spec must serve via ContinuousBatcher"
        assert all(c.ok for c in comps), [c.error for c in comps]
        dense = engine.chat(
            [_req("tpu://dense-tiny", r.user) for r in reqs], PARAMS
        )
        # Greedy decode: paged continuous serving must reproduce the
        # dense engine's tokens row for row.
        assert [c.text for c in comps] == [c.text for c in dense]

    def test_usage_totals_consistent(self, engine):
        # Self-contained: (re-)register the spec so the test passes alone.
        save_registry_entry(
            ModelSpec(alias="cont-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        comps = engine.chat(
            [
                _req("tpu://cont-tiny", "one"),
                _req("tpu://cont-tiny", "two two"),
            ],
            PARAMS,
        )
        assert all(c.ok for c in comps)
        for c in comps:
            assert c.usage.output_tokens == c.usage.decode_tokens
            assert c.usage.device_time_s >= c.usage.decode_time_s >= 0

    def test_paged_chat_propagates_trace_ids_to_events(self, engine):
        """The engine-seam hop of causal tracing: ChatRequest ids ride
        through chat → _chat_continuous → SchedRequest and arrive
        byte-identical on the real batcher's request events — the same
        ids the mock path stamps, so a paged CLI round resolves every
        event to one round/opponent regardless of engine."""
        import dataclasses

        from adversarial_spec_tpu import obs

        save_registry_entry(
            ModelSpec(alias="cont-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        obs.reset_stats()
        reqs = [
            dataclasses.replace(
                _req("tpu://cont-tiny", user),
                trace_id="tr-004-01",
                span_id=f"tr-004-01/s{i:02d}",
            )
            for i, user in enumerate(["alpha", "beta bee"])
        ]
        comps = engine.chat(reqs, PARAMS)
        assert all(c.ok for c in comps)
        spans_seen = {
            e["req_id"]: e["span_id"]
            for e in obs.recorder.events()
            if e["type"] == "request"
        }
        assert spans_seen == {
            0: "tr-004-01/s00",
            1: "tr-004-01/s01",
        }
        for e in obs.recorder.events():
            if e["trace_id"]:
                assert e["trace_id"] == "tr-004-01", e

    def test_timeout_returns_partial(self, engine):
        """timeout_s parity with the dense path: an expired deadline
        stops the batcher between chunks instead of draining the queue."""
        save_registry_entry(
            ModelSpec(alias="cont-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        params = SamplingParams(
            max_new_tokens=64, greedy=True, timeout_s=1e-9
        )
        comps = engine.chat(
            [_req("tpu://cont-tiny", "a"), _req("tpu://cont-tiny", "b")],
            params,
        )
        assert all(c.ok for c in comps), [c.error for c in comps]
        # Deadline already expired at loop entry: each row keeps at most
        # its admission token(s), far under the 64-token budget.
        assert all(c.usage.output_tokens < 64 for c in comps)
