"""Causal request tracing: id minting, propagation parity (mock vs real
batcher), the checked waterfall decomposition, chaos-dump trace
resolution, SLO-triggered capture, and atomic obs file writes.

The load-bearing pins: (1) ids minted by the debate layer arrive
byte-identical at the event stream on BOTH serving paths, (2) a
request's stage walls sum EXACTLY to its reported prefill+decode
timings (SchedResult fields — the decomposition is checked, not
decorative), (3) a chaos fault's auto-dump resolves to the injured
request's trace, (4) an SLO capture fires exactly once per breaching
request, and (5) no trace state leaks across CLI invocations.
"""

import io
import json

import pytest

from adversarial_spec_tpu import cli, obs
from adversarial_spec_tpu.obs import trace as trace_mod


@pytest.fixture(autouse=True)
def _spec_off_module(monkeypatch):
    """Speculation multiplies the jit programs every batcher here
    compiles and its subject is orthogonal (the PR 6 tier-1 budget
    precedent); spec-on trace coverage rides test_spec_batcher.py's
    SpecEvent assertions."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


class TestMinting:
    def test_counter_minting_is_deterministic_and_resets(self):
        trace_mod.reset()
        assert trace_mod.mint_trace(1) == "tr-001-01"
        assert trace_mod.mint_trace(2) == "tr-002-02"
        trace_mod.reset()
        assert trace_mod.mint_trace(1) == "tr-001-01"

    def test_span_embeds_trace(self):
        sid = trace_mod.mint_span("tr-003-01", 2)
        assert sid == "tr-003-01/s02"
        assert trace_mod.trace_of(sid) == "tr-003-01"
        assert trace_mod.trace_of("") == ""

    def test_seeded_minting_is_stable(self):
        trace_mod.reset()
        a = trace_mod.mint_trace(1, seed=42)
        trace_mod.reset()
        b = trace_mod.mint_trace(1, seed=42)
        assert a == b and a.startswith("tr-001-01-")
        trace_mod.reset()
        assert trace_mod.mint_trace(1, seed=43) != a

    def test_scope_restores_even_through_exceptions(self):
        trace_mod.set_ambient("outer-t", "outer-s")
        with pytest.raises(RuntimeError):
            with trace_mod.scope("t", "s"):
                assert trace_mod.get_ambient() == ("t", "s")
                raise RuntimeError("boom")
        assert trace_mod.get_ambient() == ("outer-t", "outer-s")
        trace_mod.reset()
        assert trace_mod.get_ambient() == ("", "")

    def test_emit_stamps_empty_fields_only(self):
        obs.reset_stats()
        with trace_mod.scope("amb-t", "amb-s"):
            obs.emit(obs.StepEvent(kind="decode"))
            obs.emit(
                obs.FaultEvent(seam="x", trace_id="own-t", span_id="own-s")
            )
        evs = obs.recorder.events()
        assert (evs[0]["trace_id"], evs[0]["span_id"]) == ("amb-t", "amb-s")
        # Explicit stamping wins over ambient (fault victim vs the
        # co-resident admission whose scope was active).
        assert (evs[1]["trace_id"], evs[1]["span_id"]) == ("own-t", "own-s")


class TestMockPropagation:
    def _round(self, round_num=1):
        from adversarial_spec_tpu.debate.core import run_round

        return run_round(
            "# Spec body\n\nA paragraph.",
            ["mock://critic", "mock://agree"],
            round_num=round_num,
        )

    def test_every_event_resolves_to_one_round_and_opponent(self):
        obs.reset_stats()
        result = self._round(round_num=2)
        assert result.trace_id == "tr-002-01"
        assert [r.span_id for r in result.responses] == [
            "tr-002-01/s00",
            "tr-002-01/s01",
        ]
        evs = obs.recorder.events()
        assert evs, "round emitted nothing"
        for e in evs:
            assert e["trace_id"] == "tr-002-01", e
            if e["span_id"]:
                assert e["span_id"] in (
                    "tr-002-01/s00",
                    "tr-002-01/s01",
                ), e
        # Request-scoped events carry their exact span.
        req_spans = {
            e["req_id"]: e["span_id"]
            for e in evs
            if e["type"] == "request"
        }
        assert req_spans == {0: "tr-002-01/s00", 1: "tr-002-01/s01"}

    def test_mock_waterfall_decomposition_is_exact(self):
        """Synthetic walls are exact binary fractions; the only slack
        is the dump-time 6-decimal rounding of each float (each half
        rounds independently), so the sum holds to 2 ulp of that."""
        obs.reset_stats()
        self._round()
        spans = [
            e for e in obs.recorder.events() if e["type"] == "span"
        ]
        for sid in ("tr-001-01/s00", "tr-001-01/s01"):
            # ``cancelled`` closes an early-cancelled request envelope
            # exactly like ``end`` (the agree opponent cancels under
            # the streaming default) — the decomposition must hold for
            # the truncated span set too.
            ends = {
                e["name"]: e["wall_s"]
                for e in spans
                if e["span_id"] == sid
                and e["phase"] in ("end", "cancelled")
            }
            assert (
                abs(ends["request"] - (ends["prefill"] + ends["decode"]))
                <= 2e-6
            )

    def test_ambient_clears_after_round(self):
        obs.reset_stats()
        self._round()
        assert trace_mod.get_ambient() == ("", "")

    def test_breaker_degraded_opponent_span_is_balanced(self):
        """A breaker-open opponent resolves with zero engine calls —
        its 'opponent' span must still close (begin without end would
        read as a forever-in-flight request)."""
        from adversarial_spec_tpu.debate.core import RoundConfig, run_round
        from adversarial_spec_tpu.resilience.breaker import BreakerRegistry
        from adversarial_spec_tpu.resilience.faults import FaultKind

        breakers = BreakerRegistry(
            threshold=1, cooldown_s=3600.0, clock=lambda: 0.0
        )
        breakers.record("mock://critic", ok=False, kind=FaultKind.OOM)
        obs.reset_stats()
        result = run_round(
            "# Spec",
            ["mock://critic", "mock://agree"],
            cfg=RoundConfig(breakers=breakers),
        )
        degraded = result.responses[0]
        assert degraded.error and "circuit open" in degraded.error
        phases = [
            e["phase"]
            for e in obs.recorder.events()
            if e["type"] == "span"
            and e["name"] == "opponent"
            and e["span_id"] == degraded.span_id
        ]
        assert phases == ["begin", "end"]

    def test_trace_view_checks_pass_and_catch_corruption(self, tmp_path):
        from tools.trace_view import main as trace_view_main

        obs.reset_stats()
        self._round()
        path = tmp_path / "ev.jsonl"
        obs.dump_events(str(path))
        assert trace_view_main([str(path)]) == 0
        # Corrupt one request envelope's wall: the checked
        # decomposition must fail loudly (exit 1), not render anyway.
        lines = path.read_text().splitlines()
        out = []
        for line in lines:
            e = json.loads(line)
            if (
                e["type"] == "span"
                and e["name"] == "request"
                and e["phase"] == "end"
            ):
                e["wall_s"] += 1.0
            out.append(json.dumps(e, separators=(",", ":")))
        path.write_text("\n".join(out) + "\n")
        assert trace_view_main([str(path)]) == 1
        assert trace_view_main([str(path), "--no-check"]) == 0


class TestCliNoLeak:
    def _run(self, monkeypatch, capsys, *extra):
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        code = cli.main(
            ["critique", "--models", "mock://critic", "--json", *extra]
        )
        out, _ = capsys.readouterr()
        return code, json.loads(out)

    def test_trace_ids_restart_every_invocation(self, monkeypatch, capsys):
        """One CLI invocation = one round: the trace counter resets, so
        two invocations mint the SAME ids (byte-determinism of the
        events JSONL depends on it) and the ambient context never
        leaks."""
        code1, data1 = self._run(monkeypatch, capsys)
        assert code1 == 0
        code2, data2 = self._run(monkeypatch, capsys)
        assert code2 == 0
        assert data1["trace_id"] == data2["trace_id"] == "tr-001-01"
        assert trace_mod.get_ambient() == ("", "")

    def test_slo_flags_do_not_leak(self, monkeypatch, capsys):
        code, data = self._run(
            monkeypatch, capsys, "--slo-ttft-ms", "0.001"
        )
        assert code == 0
        assert data["perf"]["obs"]["slo"]["ttft_ms"] == 0.001
        assert data["perf"]["obs"]["slo"]["breaches"].get("ttft") == 1
        code, data = self._run(monkeypatch, capsys)
        assert code == 0
        assert data["perf"]["obs"]["slo"] == {
            "ttft_ms": 0.0,
            "round_s": 0.0,
            "breaches": {},
        }


class TestSloCapture:
    def test_fires_exactly_once_per_breaching_request(self, tmp_path):
        obs.configure(
            events_out=str(tmp_path / "ev.jsonl"), slo_ttft_ms=1.0
        )
        obs.reset_stats()
        with trace_mod.scope("tr-001-01", ""):
            obs.emit(obs.StepEvent(kind="decode"))
        path = obs.slo_check("ttft", "tr-001-01/s00", 0.5)
        assert path == str(tmp_path / "ev.slo_ttft.jsonl")
        # Same request again: no second capture, count stays 1.
        assert obs.slo_check("ttft", "tr-001-01/s00", 0.9) is None
        # A different request captures independently.
        assert obs.slo_check("ttft", "tr-001-01/s01", 0.5) is not None
        snap = obs.metrics.snapshot()
        assert snap['advspec_slo_breaches_total{kind="ttft"}'] == 2
        assert obs.slo_breaches() == {"ttft": 2}

    def test_capture_is_scoped_to_the_breaching_trace(self, tmp_path):
        obs.configure(
            events_out=str(tmp_path / "ev.jsonl"), slo_round_s=0.001
        )
        obs.reset_stats()
        with trace_mod.scope("tr-001-01", ""):
            obs.emit(obs.StepEvent(kind="decode"))
        with trace_mod.scope("tr-002-02", ""):
            obs.emit(obs.StepEvent(kind="decode"))
        assert obs.slo_check("round", "tr-002-02/s00", 0.5) is not None
        dumped = [
            json.loads(line)
            for line in (tmp_path / "ev.slo_round.jsonl")
            .read_text()
            .splitlines()
        ]
        assert dumped, "SLO capture wrote nothing"
        assert all(e["trace_id"] == "tr-002-02" for e in dumped)

    def test_disabled_budgets_never_fire(self):
        obs.configure(slo_ttft_ms=0.0, slo_round_s=0.0)
        obs.reset_stats()
        assert obs.slo_check("ttft", "s", 1e9) is None
        assert obs.slo_check("round", "s", 1e9) is None
        assert obs.slo_breaches() == {}

    def test_mock_round_breaches_and_captures(self, tmp_path):
        """End-to-end on the mock: synthetic prefill walls (~0.29s)
        breach a 1ms TTFT budget — one capture per opponent request,
        scoped to the round's trace."""
        from adversarial_spec_tpu.debate.core import run_round

        obs.configure(
            events_out=str(tmp_path / "ev.jsonl"), slo_ttft_ms=1.0
        )
        obs.reset_stats()
        result = run_round(
            "# Spec body", ["mock://critic", "mock://agree"], round_num=1
        )
        assert obs.slo_breaches() == {"ttft": 2}
        cap = tmp_path / "ev.slo_ttft.jsonl"
        assert cap.exists()
        dumped = [
            json.loads(line) for line in cap.read_text().splitlines()
        ]
        assert all(e["trace_id"] == result.trace_id for e in dumped)


class TestAtomicWrites:
    def test_write_metrics_crash_window_leaves_old_file_intact(
        self, tmp_path, monkeypatch
    ):
        """The scraper contract: a writer dying anywhere before the
        rename leaves the PREVIOUS complete exposition in place and no
        half-written target — tmp+rename, DiskStore's discipline."""
        import os as os_mod

        target = tmp_path / "metrics.prom"
        target.write_text("previous complete exposition\n")
        obs.reset_stats()
        obs.metrics.counter("advspec_x_total").inc()

        def boom(src, dst):
            raise OSError("crash inside the rename window")

        monkeypatch.setattr(os_mod, "replace", boom)
        with pytest.raises(OSError):
            obs.write_metrics(str(target))
        monkeypatch.undo()
        assert target.read_text() == "previous complete exposition\n"
        # The failed attempt's temp file is cleaned up, not orphaned
        # as a live path a scraper could mistake for the exposition.
        assert list(tmp_path.iterdir()) == [target]
        # And a healthy write lands atomically with the new content.
        obs.write_metrics(str(target))
        assert "advspec_x_total 1" in target.read_text()
        assert list(tmp_path.iterdir()) == [target]

    def test_dump_events_crash_window(self, tmp_path, monkeypatch):
        import os as os_mod

        target = tmp_path / "ev.jsonl"
        target.write_text('{"seq":1,"type":"old"}\n')
        obs.reset_stats()
        obs.emit(obs.StepEvent(kind="decode"))

        def boom(src, dst):
            raise OSError("crash inside the rename window")

        monkeypatch.setattr(os_mod, "replace", boom)
        with pytest.raises(OSError):
            obs.dump_events(str(target))
        monkeypatch.undo()
        assert target.read_text() == '{"seq":1,"type":"old"}\n'
        assert list(tmp_path.iterdir()) == [target]
        assert obs.dump_events(str(target)) == 1


class TestBatcherPropagation:
    @pytest.fixture(scope="class")
    def tiny_model(self):
        import jax
        import jax.numpy as jnp

        from adversarial_spec_tpu.models import transformer as T
        from adversarial_spec_tpu.models.config import get_config

        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        return params, cfg

    def _batcher(self, params, cfg, **kw):
        from adversarial_spec_tpu.engine.scheduler import ContinuousBatcher

        return ContinuousBatcher(
            params, cfg, max_batch=2, max_new_cap=8, chunk=4, **kw
        )

    def _submit_two(self, b):
        from adversarial_spec_tpu.engine.scheduler import SchedRequest

        b.submit(
            SchedRequest(
                req_id=0,
                prompt_ids=[1, 5, 9],
                max_new_tokens=6,
                trace_id="tr-001-01",
                span_id="tr-001-01/s00",
            )
        )
        b.submit(
            SchedRequest(
                req_id=1,
                prompt_ids=[2, 6],
                max_new_tokens=6,
                trace_id="tr-001-01",
                span_id="tr-001-01/s01",
            )
        )

    def test_ids_propagate_verbatim_to_every_request_event(
        self, tiny_model
    ):
        """Parity with the mock path: the ids minted above the engine
        arrive byte-identical in the real batcher's event stream and on
        its SchedResults."""
        params, cfg = tiny_model
        obs.reset_stats()
        b = self._batcher(params, cfg)
        self._submit_two(b)
        results = b.run_all()
        assert [(r.trace_id, r.span_id) for r in results] == [
            ("tr-001-01", "tr-001-01/s00"),
            ("tr-001-01", "tr-001-01/s01"),
        ]
        evs = obs.recorder.events()
        by_req = {}
        for e in evs:
            if e["type"] in ("request", "spec", "fault") and e.get(
                "req_id", -1
            ) >= 0:
                by_req.setdefault(e["req_id"], set()).add(e["span_id"])
        assert by_req[0] == {"tr-001-01/s00"}
        assert by_req[1] == {"tr-001-01/s01"}
        # Cache events (ambient-stamped) resolve to an admission, and
        # every stamped event resolves to the one round.
        for e in evs:
            if e["trace_id"]:
                assert e["trace_id"] == "tr-001-01", e
            if e["type"] == "cache":
                assert e["span_id"] in (
                    "tr-001-01/s00",
                    "tr-001-01/s01",
                ), e

    def test_decomposition_matches_sched_result_exactly(
        self, tiny_model, tmp_path
    ):
        """The acceptance pin: waterfall stage walls sum to the
        request's REPORTED prefill+decode timings (SchedResult fields),
        and the slot decode sums reproduce the batcher's decode
        counter."""
        from tools.trace_view import (
            check_decomposition,
            collect_requests,
            main as trace_view_main,
        )

        params, cfg = tiny_model
        obs.reset_stats()
        b = self._batcher(params, cfg)
        self._submit_two(b)
        results = b.run_all()
        assert abs(
            sum(r.decode_time_s for r in results) - b.decode_time_s
        ) < 1e-9
        evs = obs.recorder.events()
        reqs = collect_requests(evs)
        assert set(reqs) == {"tr-001-01/s00", "tr-001-01/s01"}
        for r in results:
            rec = reqs[r.span_id]
            assert rec["stages"]["prefill"] == round(r.prefill_time_s, 6)
            assert rec["stages"]["decode"] == round(r.decode_time_s, 6)
            assert rec["request_wall"] == round(
                r.prefill_time_s + r.decode_time_s, 6
            )
        assert check_decomposition(reqs) == []
        path = tmp_path / "ev.jsonl"
        obs.dump_events(str(path))
        assert trace_view_main([str(path)]) == 0

    def test_legacy_loop_decomposition_holds(self, tiny_model):
        from tools.trace_view import check_decomposition, collect_requests

        params, cfg = tiny_model
        obs.reset_stats()
        b = self._batcher(params, cfg, interleave=False)
        self._submit_two(b)
        results = b.run_all()
        assert abs(
            sum(r.decode_time_s for r in results) - b.decode_time_s
        ) < 1e-9
        reqs = collect_requests(obs.recorder.events())
        assert check_decomposition(reqs) == []
        assert {r.span_id for r in results} == set(reqs)

    def test_slo_round_breach_captures_on_real_batcher(
        self, tiny_model, tmp_path
    ):
        params, cfg = tiny_model
        obs.configure(
            events_out=str(tmp_path / "ev.jsonl"), slo_round_s=1e-9
        )
        obs.reset_stats()
        b = self._batcher(params, cfg)
        self._submit_two(b)
        b.run_all()
        assert obs.slo_breaches()["round"] == 2
        cap = tmp_path / "ev.slo_round.jsonl"
        assert cap.exists()
        dumped = [
            json.loads(line) for line in cap.read_text().splitlines()
        ]
        assert dumped and all(
            e["trace_id"] == "tr-001-01" for e in dumped
        )

    def test_chaos_kv_alloc_dump_resolves_to_injured_trace(
        self, tiny_model, tmp_path
    ):
        """Acceptance: the chaos fault's auto-dump JSONL resolves to
        the INJURED request's trace/span — the FaultEvent and the
        evicted lifecycle row both carry them."""
        from adversarial_spec_tpu.resilience import injector as injector_mod
        from adversarial_spec_tpu.resilience.injector import (
            FaultInjector,
            parse_chaos_spec,
        )

        params, cfg = tiny_model
        obs.configure(events_out=str(tmp_path / "flight.jsonl"))
        obs.reset_stats()
        try:
            injector_mod.install(
                FaultInjector(parse_chaos_spec("bug@kv_alloc:times=1"))
            )
            b = self._batcher(params, cfg)
            self._submit_two(b)
            results = b.run_all()
        finally:
            injector_mod.reset()
            obs.configure(events_out="")
        assert results[0].fault_kind == "bug"
        assert results[0].span_id == "tr-001-01/s00"
        dump = tmp_path / "flight.fault.jsonl"
        assert dump.exists()
        events = [
            json.loads(line) for line in dump.read_text().splitlines()
        ]
        for e in events:
            assert obs.validate_event(e) == [], e
        fe = [e for e in events if e["type"] == "fault"][-1]
        assert fe["seam"] == "kv_alloc"
        assert fe["trace_id"] == "tr-001-01"
        assert fe["span_id"] == "tr-001-01/s00"
        evicted = [
            e
            for e in events
            if e["type"] == "request" and e["state"] == "evicted"
        ][-1]
        assert evicted["span_id"] == "tr-001-01/s00"

    def test_chaos_scheduler_chunk_dump_resolves_to_victim_trace(
        self, tiny_model, tmp_path
    ):
        """A decode-side fault evicts a victim chosen at fault time —
        its FaultEvent must stamp the VICTIM's span, not whatever
        admission scope was ambient."""
        from adversarial_spec_tpu.resilience import injector as injector_mod
        from adversarial_spec_tpu.resilience.injector import (
            FaultInjector,
            parse_chaos_spec,
        )

        params, cfg = tiny_model
        obs.configure(events_out=str(tmp_path / "flight.jsonl"))
        obs.reset_stats()
        try:
            injector_mod.install(
                FaultInjector(
                    parse_chaos_spec("bug@scheduler_chunk:after=1:times=1")
                )
            )
            b = self._batcher(params, cfg)
            self._submit_two(b)
            results = b.run_all()
        finally:
            injector_mod.reset()
            obs.configure(events_out="")
        victims = [r for r in results if r.fault_kind is not None]
        assert victims, "chaos fault did not evict anyone"
        dump = tmp_path / "flight.fault.jsonl"
        assert dump.exists()
        events = [
            json.loads(line) for line in dump.read_text().splitlines()
        ]
        fe = [e for e in events if e["type"] == "fault"][-1]
        assert fe["span_id"] == victims[0].span_id
        assert fe["trace_id"] == victims[0].trace_id == "tr-001-01"
