"""Tracing subsystem tests + CLI perf-block integration."""

import io
import json
import time

from adversarial_spec_tpu.utils.tracing import Tracer, maybe_profile
from adversarial_spec_tpu import cli


class TestTracer:
    def test_span_accumulates(self):
        t = Tracer()
        with t.span("a"):
            time.sleep(0.01)
        with t.span("a"):
            time.sleep(0.01)
        assert t.spans["a"] >= 0.02

    def test_nested_spans(self):
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                time.sleep(0.005)
        assert t.spans["outer"] >= t.spans["inner"]

    def test_span_records_on_exception(self):
        t = Tracer()
        try:
            with t.span("boom"):
                raise RuntimeError
        except RuntimeError:
            pass
        assert "boom" in t.spans

    def test_counters_and_rate(self):
        t = Tracer()
        t.count("tokens", 50)
        t.count("tokens", 50)
        t.spans["decode"] = 2.0
        assert t.rate("tokens", "decode") == 50.0
        assert t.rate("tokens", "absent") == 0.0

    def test_report_shape(self):
        t = Tracer()
        with t.span("x"):
            pass
        t.count("n", 3)
        rep = t.report()
        assert "total_s" in rep and "x" in rep["spans"]
        assert rep["counters"]["n"] == 3

    def test_span_call_counts_in_report(self):
        """A span entered twice reports BOTH the accumulated seconds
        and the entry count — without the count, averages (per-chat
        latency from N chats) were impossible to reconstruct."""
        t = Tracer()
        for _ in range(3):
            with t.span("chat"):
                pass
        with t.span("validate"):
            pass
        rep = t.report()
        assert rep["span_counts"]["chat"] == 3
        assert rep["span_counts"]["validate"] == 1
        # The average is now computable: spans[k] / span_counts[k].
        assert rep["spans"]["chat"] >= 0.0
        # Directly-assigned spans (cli sets tracer.spans["decode"])
        # simply have no count — absent, not wrong.
        t.spans["decode"] = 1.0
        assert "decode" not in t.report()["span_counts"]

    def test_nested_span_tree(self):
        t = Tracer()
        with t.span("round"):
            with t.span("chat"):
                time.sleep(0.002)
            with t.span("chat"):
                pass
        tree = t.report()["span_tree"]
        assert tree["round"]["count"] == 1
        assert tree["round"]["children"]["chat"]["count"] == 2
        assert (
            tree["round"]["total_s"]
            >= tree["round"]["children"]["chat"]["total_s"]
        )
        # Flat view unchanged: both levels visible as before.
        assert "round" in t.spans and "chat" in t.spans

    def test_merge_with_prefix(self):
        """Per-opponent debate spans graft under the CLI tracer's
        'debate' node — one report, two layers."""
        child = Tracer()
        child.add_span("opponent/mock://critic", 0.5)
        child.add_span("opponent/mock://critic", 0.25)
        child.count("attempts.mock://critic", 2)
        parent = Tracer()
        with parent.span("round"):
            pass
        parent.merge(child, prefix="debate")
        assert parent.spans["debate/opponent/mock://critic"] == 0.75
        assert parent.span_counts["debate/opponent/mock://critic"] == 2
        assert parent.counters["debate/attempts.mock://critic"] == 2
        tree = parent.report()["span_tree"]
        assert (
            tree["debate"]["children"]["opponent/mock://critic"]["count"]
            == 2
        )

    def test_merge_without_prefix_accumulates(self):
        a, b = Tracer(), Tracer()
        with a.span("x"):
            pass
        with b.span("x"):
            pass
        a.merge(b)
        assert a.span_counts["x"] == 2
        assert a.report()["span_tree"]["x"]["count"] == 2

    def test_maybe_profile_noop(self):
        with maybe_profile(None):
            pass  # must not require jax or a directory


class TestCliPerfBlock:
    def test_json_output_has_perf(self, monkeypatch, capsys):
        monkeypatch.setattr("sys.stdin", io.StringIO("# Spec"))
        code = cli.main(
            ["critique", "--models", "mock://critic?tps=100", "--json"]
        )
        out, err = capsys.readouterr()
        assert code == 0
        data = json.loads(out)
        assert "perf" in data
        assert "round" in data["perf"]["spans"]
        assert data["perf"]["decode_tokens_per_sec"] > 0
        assert "perf:" in err  # human line on stderr


class TestMutationHardening:
    """Pins that kill the tracing.py mutation survivors."""

    def test_span_and_total_are_durations(self):
        """now - start, not now + start (an Add mutant reports ~2x the
        monotonic clock, absurdly larger than any real round)."""
        import time as _time

        t = Tracer()
        with t.span("s"):
            _time.sleep(0.02)
        assert 0.01 < t.spans["s"] < 10.0
        assert 0.0 <= t.report()["total_s"] < 10.0

    def test_report_rounding_digits(self, monkeypatch):
        """total_s/spans round to 4 digits, counters to 2."""
        from adversarial_spec_tpu.utils import tracing as tr

        monkeypatch.setattr(tr.time, "monotonic", lambda: 0.123456)
        t = Tracer(_t0=0.0)
        t.spans["k"] = 0.123456
        t.count("c", 0.126)
        rep = t.report()
        assert rep["total_s"] == 0.1235
        assert rep["spans"]["k"] == 0.1235
        assert rep["counters"]["c"] == 0.13

    def test_maybe_profile_gates_on_dir(self, monkeypatch, tmp_path):
        """A trace dir engages jax.profiler; None must not."""
        import contextlib

        import jax

        traced = []

        @contextlib.contextmanager
        def fake_trace(d):
            traced.append(d)
            yield

        monkeypatch.setattr(jax.profiler, "trace", fake_trace)
        with maybe_profile(None):
            pass
        assert traced == []
        with maybe_profile(str(tmp_path)):
            pass
        assert traced == [str(tmp_path)]
