"""Transformer numerics tests.

The reference tests everything above its transport seam with fakes
(SURVEY §4); our model layer has no reference analog, so the ground truth
here is (a) self-consistency — incremental decode must reproduce the full
forward — and (b) parity with the HuggingFace torch implementations of the
same architectures on tiny random checkpoints.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config

FAMILIES = ["llama", "mistral", "gemma2", "qwen2"]


def _full_forward(params, cfg, ids, total_len):
    B, S = ids.shape
    cache = T.init_cache(cfg, B, total_len, dtype=jnp.float32)
    positions = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None, :], (B, 1))
    kv_valid = jnp.arange(total_len)[None, :] < total_len
    return T.forward(
        params, cfg, ids, positions, cache, jnp.int32(0), kv_valid
    )


@pytest.mark.parametrize("family", FAMILIES)
def test_incremental_decode_matches_full_forward(family):
    """Prefill(prefix) + per-token decode must equal one full forward."""
    cfg = get_config(family, "tiny")
    rng = jax.random.key(0)
    params = T.init_params(rng, cfg, dtype=jnp.float32)
    S, extra = 8, 4
    total = S + extra
    ids = jax.random.randint(jax.random.key(1), (1, total), 0, cfg.vocab_size)

    full_logits, _ = _full_forward(params, cfg, ids, total)

    # Prefill on the first S tokens, then decode the rest one at a time.
    cache = T.init_cache(cfg, 1, total, dtype=jnp.float32)
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]
    kv_valid = jnp.arange(total)[None, :] >= 0
    logits, cache = T.forward(
        params, cfg, ids[:, :S], positions, cache, jnp.int32(0), kv_valid
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full_logits[:, :S]), rtol=2e-4, atol=2e-4
    )
    for i in range(extra):
        pos = jnp.array([[S + i]], dtype=jnp.int32)
        step_logits, cache = T.forward(
            params,
            cfg,
            ids[:, S + i : S + i + 1],
            pos,
            cache,
            jnp.int32(S + i),
            kv_valid,
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full_logits[:, S + i]),
            rtol=2e-4,
            atol=2e-4,
        )


def test_left_padding_invariance():
    """A row's logits must not depend on how much left-padding it has."""
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    seq = jax.random.randint(jax.random.key(2), (1, 6), 0, cfg.vocab_size)
    total = 16

    def run(pad):
        S = pad + 6
        ids = jnp.concatenate(
            [jnp.zeros((1, pad), jnp.int32), seq], axis=1
        )
        cache = T.init_cache(cfg, 1, total, dtype=jnp.float32)
        positions = jnp.maximum(
            jnp.arange(S, dtype=jnp.int32)[None, :] - pad, 0
        )
        kv_valid = jnp.arange(total)[None, :] >= pad
        logits, _ = T.forward(
            params, cfg, ids, positions, cache, jnp.int32(0), kv_valid
        )
        return np.asarray(logits[:, -1])

    np.testing.assert_allclose(run(0), run(5), rtol=2e-4, atol=2e-4)


def test_sliding_window_masks_distant_tokens():
    """With a window of W, logits at position p must ignore tokens < p-W."""
    cfg = get_config("mistral", "tiny")  # window 128 — shrink via replace
    from dataclasses import replace

    cfg = replace(cfg, sliding_window=4, n_layers=1)
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    total = 12
    ids_a = jax.random.randint(jax.random.key(3), (1, total), 0, cfg.vocab_size)
    # Change a token far outside the window of the last position.
    ids_b = ids_a.at[0, 0].set((ids_a[0, 0] + 1) % cfg.vocab_size)

    la, _ = _full_forward(params, cfg, ids_a, total)
    lb, _ = _full_forward(params, cfg, ids_b, total)
    # Last position attends only to the final 4 slots — identical logits.
    np.testing.assert_allclose(
        np.asarray(la[:, -1]), np.asarray(lb[:, -1]), rtol=1e-5, atol=1e-5
    )
    # But an early position does see the change.
    assert not np.allclose(np.asarray(la[:, 1]), np.asarray(lb[:, 1]))


@pytest.mark.parametrize(
    "family,hf_name",
    [("llama", "llama"), ("qwen2", "qwen2"), ("mistral", "mistral"),
     ("gemma2", "gemma2")],
)
def test_hf_parity_tiny(family, hf_name, tmp_path):
    """Our forward must match transformers' torch forward on the same
    random tiny checkpoint (validates both the architecture flags and the
    loader's weight mapping/transposes)."""
    torch = pytest.importorskip("torch")
    import transformers

    cfg = get_config(family, "tiny")
    kwargs = dict(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        intermediate_size=cfg.ffn_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps,
        max_position_embeddings=256,
        tie_word_embeddings=cfg.tied_embeddings,
    )
    if family == "llama":
        hf_cfg = transformers.LlamaConfig(**kwargs)
    elif family == "qwen2":
        hf_cfg = transformers.Qwen2Config(**kwargs)
    elif family == "mistral":
        hf_cfg = transformers.MistralConfig(
            **kwargs, sliding_window=cfg.sliding_window
        )
    else:
        hf_cfg = transformers.Gemma2Config(
            **kwargs,
            head_dim=cfg.head_dim,
            hidden_activation="gelu_pytorch_tanh",
            query_pre_attn_scalar=cfg.head_dim,
            attn_logit_softcapping=cfg.attn_softcap,
            final_logit_softcapping=cfg.logit_softcap,
            sliding_window=cfg.sliding_window,
        )
    torch.manual_seed(0)
    hf_model = transformers.AutoModelForCausalLM.from_config(hf_cfg)
    hf_model.eval()
    ckpt = tmp_path / "ckpt"
    hf_model.save_pretrained(ckpt, safe_serialization=True)

    from adversarial_spec_tpu.engine.loader import load_hf_checkpoint

    params = load_hf_checkpoint(ckpt, cfg, family, dtype=jnp.float32)

    ids = np.array([[1, 7, 42, 9, 100, 3, 250, 11]], dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(ids)).logits.numpy()

    ours, _ = _full_forward(params, cfg, jnp.asarray(ids, jnp.int32), 8)
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=2e-3, atol=2e-3
    )


def test_attn_scale_override():
    """Gemma-2-27B scales queries by 1/sqrt(dim/n_heads)=1/sqrt(144), not
    1/sqrt(head_dim)=1/sqrt(128); other configs use head_dim."""
    import math

    c27 = get_config("gemma2", "27b")
    assert c27.query_pre_attn_scalar == 144.0
    assert abs(c27.attn_scale - 1 / math.sqrt(144)) < 1e-12
    c9 = get_config("gemma2", "9b")
    assert abs(c9.attn_scale - 1 / math.sqrt(c9.head_dim)) < 1e-12
    cl = get_config("llama", "8b")
    assert abs(cl.attn_scale - 1 / math.sqrt(cl.head_dim)) < 1e-12


def test_scale_changes_logits():
    """The configured attention scale must actually reach the kernels:
    same weights, different query_pre_attn_scalar → different logits."""
    from dataclasses import replace

    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    ids = jnp.array([[1, 7, 42, 9]], jnp.int32)
    a, _ = _full_forward(params, cfg, ids, 4)
    cfg2 = replace(cfg, query_pre_attn_scalar=float(cfg.head_dim) * 4)
    b, _ = _full_forward(params, cfg2, ids, 4)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_count_params():
    cfg = get_config("llama", "tiny")
    params = T.init_params(jax.random.key(0), cfg)
    n = T.count_params(params)
    assert n > 0
    # Embedding + lm_head dominate: V*D*2 = 512*256*2.
    assert n > 2 * cfg.vocab_size * cfg.dim


class TestRopeScaling:
    """Llama-3.1/3.2 rope scaling (ops/rope.py:_llama3_scale)."""

    def test_llama3_scaling_matches_hf_formula(self):
        """Independent numpy re-derivation of HF rope_type="llama3"."""
        from adversarial_spec_tpu.ops.rope import rope_angles

        head_dim, theta = 64, 500000.0
        factor, low, high, orig = 32.0, 1.0, 4.0, 8192.0
        half = head_dim // 2
        freqs = 1.0 / theta ** (np.arange(half, dtype=np.float64) / half)
        # HF modeling_rope_utils._compute_llama3_parameters, re-derived.
        low_wl = orig / low
        high_wl = orig / high
        expected = []
        for f in freqs:
            wl = 2 * np.pi / f
            if wl < high_wl:
                expected.append(f)
            elif wl > low_wl:
                expected.append(f / factor)
            else:
                smooth = (orig / wl - low) / (high - low)
                expected.append((1 - smooth) * f / factor + smooth * f)
        expected = np.asarray(expected)

        pos = jnp.array([1.0])
        cos, sin = rope_angles(
            pos, head_dim, theta, scaling=(factor, low, high, orig)
        )
        # At position 1, angle == scaled frequency.
        got = np.arctan2(np.asarray(sin[0]), np.asarray(cos[0]))
        np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_scaling_changes_low_freqs_only(self):
        from adversarial_spec_tpu.ops.rope import rope_angles

        pos = jnp.array([100.0])
        plain = rope_angles(pos, 64, 500000.0)
        scaled = rope_angles(
            pos, 64, 500000.0, scaling=(32.0, 1.0, 4.0, 8192.0)
        )
        # Highest-frequency component (index 0) is untouched.
        np.testing.assert_allclose(plain[0][0, 0], scaled[0][0, 0])
        # Lowest-frequency component is stretched (angle shrinks).
        assert abs(float(scaled[1][0, -1])) < abs(float(plain[1][0, -1]))

    def test_named_configs_are_checkpoint_consistent(self):
        """ADVICE r1: each named config matches ONE real checkpoint gen."""
        c1b = get_config("llama", "1b")
        assert c1b.tied_embeddings and c1b.rope_scaling is not None
        c3b = get_config("llama", "3b")
        assert c3b.tied_embeddings and c3b.rope_scaling is not None
        c8b = get_config("llama", "8b")
        assert not c8b.tied_embeddings and c8b.rope_scaling is None
        # Mistral-7B v0.3: theta 1e6, NO sliding window, 32768 vocab.
        m7b = get_config("mistral", "7b")
        assert m7b.rope_theta == 1000000.0 and m7b.sliding_window == 0
        assert m7b.vocab_size == 32768


def test_hf_parity_llama3_rope_scaling(tmp_path):
    """Llama-3.2-style rope scaling (HF rope_type="llama3") against the
    real transformers implementation — long positions are where scaled
    and unscaled frequencies diverge, so the prompt exceeds the original
    8-position window the test config declares."""
    torch = pytest.importorskip("torch")
    import transformers
    from dataclasses import replace

    cfg = replace(
        get_config("llama", "tiny"),
        tied_embeddings=True,
        rope_scaling_factor=32.0,
        rope_original_max=8,  # tiny "original" window: positions past 8
        max_seq_len=256,      # exercise the scaled regime immediately
    )
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.dim,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        intermediate_size=cfg.ffn_dim,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps,
        max_position_embeddings=256,
        tie_word_embeddings=True,
        rope_scaling={
            "rope_type": "llama3",
            "factor": cfg.rope_scaling_factor,
            "low_freq_factor": cfg.rope_low_freq_factor,
            "high_freq_factor": cfg.rope_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_original_max,
        },
    )
    torch.manual_seed(1)
    hf_model = transformers.AutoModelForCausalLM.from_config(hf_cfg)
    hf_model.eval()
    ckpt = tmp_path / "ckpt"
    hf_model.save_pretrained(ckpt, safe_serialization=True)

    from adversarial_spec_tpu.engine.loader import load_hf_checkpoint

    params = load_hf_checkpoint(ckpt, cfg, "llama", dtype=jnp.float32)

    S = 24  # well past rope_original_max=8
    rng = np.random.default_rng(7)
    ids = rng.integers(1, cfg.vocab_size, (1, S))
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(ids)).logits.numpy()

    ours, _ = _full_forward(params, cfg, jnp.asarray(ids, jnp.int32), S)
    np.testing.assert_allclose(
        np.asarray(ours), hf_logits, rtol=2e-3, atol=2e-3
    )
    # Guard: scaling genuinely changes the output in this regime (the
    # parity above must not be vacuous).
    unscaled = replace(cfg, rope_scaling_factor=0.0)
    ours_unscaled, _ = _full_forward(
        params, unscaled, jnp.asarray(ids, jnp.int32), S
    )
    assert not np.allclose(
        np.asarray(ours), np.asarray(ours_unscaled), atol=1e-3
    )


class TestTransposedHead:
    """Tied-embedding configs materialize a [D, V] head copy at init/load
    (full-bandwidth decode matmul); it must be numerically interchangeable
    with the einsum over the [V, D] embed table."""

    def _tied_cfg(self):
        from dataclasses import replace

        return replace(get_config("llama", "tiny"), tied_embeddings=True)

    def test_logits_parity_with_einsum_path(self):
        cfg = self._tied_cfg()
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        assert "lm_head_t" in params
        ids = jnp.asarray([[3, 5, 7, 11]], jnp.int32)
        fast, _ = _full_forward(params, cfg, ids, ids.shape[1])
        slow_params = {k: v for k, v in params.items() if k != "lm_head_t"}
        slow, _ = _full_forward(slow_params, cfg, ids, ids.shape[1])
        np.testing.assert_allclose(
            np.asarray(fast), np.asarray(slow), rtol=1e-5, atol=1e-5
        )

    def test_optional(self):
        cfg = self._tied_cfg()
        params = T.init_params(
            jax.random.key(0), cfg, dtype=jnp.float32, transposed_head=False
        )
        assert "lm_head_t" not in params

    def test_loader_materializes_transposed_head(self, tmp_path):
        torch = pytest.importorskip("torch")
        import transformers

        cfg = self._tied_cfg()
        hf_cfg = transformers.LlamaConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.dim,
            num_hidden_layers=cfg.n_layers,
            num_attention_heads=cfg.n_heads,
            num_key_value_heads=cfg.n_kv_heads,
            intermediate_size=cfg.ffn_dim,
            rope_theta=cfg.rope_theta,
            rms_norm_eps=cfg.rms_eps,
            tie_word_embeddings=True,
        )
        torch.manual_seed(0)
        hf_model = transformers.AutoModelForCausalLM.from_config(hf_cfg)
        ckpt = tmp_path / "ckpt"
        hf_model.save_pretrained(ckpt, safe_serialization=True)

        from adversarial_spec_tpu.engine.loader import load_hf_checkpoint

        params = load_hf_checkpoint(ckpt, cfg, "llama", dtype=jnp.float32)
        assert "lm_head_t" in params
        np.testing.assert_array_equal(
            np.asarray(params["lm_head_t"]),
            np.asarray(params["embed"]).T,
        )
