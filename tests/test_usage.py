"""Usage/CostTracker tests (reference analog: CostTracker tests in
tests/test_models.py — including the documented '/ to *' mutation kill)."""

from adversarial_spec_tpu.debate.usage import (
    CostTracker,
    Usage,
    model_cost_rates,
)


class TestUsage:
    def test_add(self):
        a = Usage(input_tokens=10, output_tokens=5, device_time_s=1.0)
        b = Usage(input_tokens=1, output_tokens=2, device_time_s=0.5)
        c = a + b
        assert c.input_tokens == 11
        assert c.output_tokens == 7
        assert c.device_time_s == 1.5

    def test_total_tokens(self):
        assert Usage(input_tokens=3, output_tokens=4).total_tokens == 7

    def test_cost_division_by_million(self):
        # Mutation kill: '/' → '*' would make this astronomically large.
        u = Usage(input_tokens=1_000_000, output_tokens=1_000_000)
        assert u.cost_for("mock://critic") == 3.0  # $1 in + $2 out

    def test_tpu_models_are_free(self):
        u = Usage(input_tokens=1_000_000, output_tokens=1_000_000)
        assert u.cost_for("tpu://random-8b") == 0.0

    def test_unknown_model_default_cost(self):
        assert Usage(input_tokens=1000).cost_for("unknown://x") == 0.0


class TestModelCostRates:
    def test_longest_prefix_wins(self):
        assert model_cost_rates("mock://critic?agree_after=2") == (1.0, 2.0)

    def test_bare_prefix(self):
        assert model_cost_rates("mock://other") == (1.0, 2.0)


class TestCostTracker:
    def test_accumulates_per_model(self):
        t = CostTracker()
        t.add("m1", Usage(input_tokens=10, output_tokens=1))
        t.add("m1", Usage(input_tokens=5, output_tokens=2))
        t.add("m2", Usage(input_tokens=7))
        assert t.by_model["m1"].input_tokens == 15
        assert t.by_model["m1"].output_tokens == 3
        assert t.by_model["m2"].input_tokens == 7
        assert t.total_usage.total_tokens == 25

    def test_total_cost(self):
        t = CostTracker()
        t.add("mock://a", Usage(input_tokens=2_000_000))
        t.add("tpu://x", Usage(input_tokens=2_000_000))
        assert t.total_cost == 2.0

    def test_tokens_per_sec(self):
        t = CostTracker()
        t.add("m", Usage(decode_tokens=100, decode_time_s=2.0))
        assert t.tokens_per_sec() == 50.0
        assert t.tokens_per_sec("m") == 50.0
        assert t.tokens_per_sec("absent") == 0.0

    def test_report_shape(self):
        t = CostTracker()
        t.add("m", Usage(input_tokens=1, output_tokens=2, device_time_s=0.1))
        rep = t.report()
        assert set(rep) == {
            "models",
            "total_tokens",
            "total_cost_usd",
            "total_device_time_s",
        }
        assert rep["models"]["m"]["input_tokens"] == 1
        assert "cost_usd" in rep["models"]["m"]

    def test_format_text_contains_total(self):
        t = CostTracker()
        t.add("m", Usage(input_tokens=1, output_tokens=1))
        text = t.format_text()
        assert "TOTAL" in text and "m:" in text


class TestMutationHardening:
    """Pins that kill the round-5 mutation-sweep survivors
    (tools/mutation_run.py; each assertion names the mutant it kills)."""

    def test_price_table_prefixes(self):
        """Kills MODEL_COSTS key mutants: the scheme prefixes are the
        price-lookup contract (mock bills, tpu is free)."""
        assert model_cost_rates("mock://anything?x=1") == (1.0, 2.0)
        assert model_cost_rates("tpu://llama-8b") == (0.0, 0.0)
        assert model_cost_rates("unknown://m") == (0.0, 0.0)

    def test_to_dict_schema_and_rounding(self):
        """Kills to_dict key mutants and the round(_, 4) digit mutant —
        the dict is the per-model block of the --json cost report."""
        u = Usage(
            input_tokens=3,
            output_tokens=5,
            device_time_s=0.123456,
            cached_tokens=2,
            prefill_time_s=0.05,
        )
        assert u.to_dict() == {
            "input_tokens": 3,
            "output_tokens": 5,
            "total_tokens": 8,
            "cached_tokens": 2,
            "device_time_s": 0.1235,
            "prefill_time_s": 0.05,
            "decode_time_s": 0.0,
        }

    def test_report_device_time_rounding(self):
        t = CostTracker()
        t.add("tpu://m", Usage(device_time_s=0.123456))
        assert t.report()["total_device_time_s"] == 0.1235

    def test_tokens_per_sec_boundaries(self):
        """Kills the L112 zero mutants: sub-second decode times count
        (0 -> 1 in the guard) and the no-data answer is 0.0."""
        t = CostTracker()
        assert t.tokens_per_sec() == 0.0
        t.add("m", Usage(decode_tokens=1, decode_time_s=0.5))
        assert t.tokens_per_sec() == 2.0

    def test_format_text_exact(self):
        """Kills the summary-string mutants: the text block is the
        --show-cost user surface."""
        t = CostTracker()
        t.add("mock://a", Usage(input_tokens=10, output_tokens=5))
        assert t.format_text() == (
            "Cost summary:\n"
            "  mock://a: 10 in / 5 out tokens, $0.0000\n"
            "  TOTAL: 15 tokens, $0.0000"
        )
