"""Weight-residency tests (ISSUE 15): the ledger state machine, the
TpuEngine demote/promote path, mock parity, scheduler coalescing, CLI
plumbing, and the graftlint registrations that pin the discipline.

The real-engine tests reuse the same tiny aliases and sampling shapes
as tests/test_tpu_engine.py so the jit cache absorbs most of the
compile cost across the suite."""

import json

import pytest

from adversarial_spec_tpu import obs
from adversarial_spec_tpu.engine import weightres
from adversarial_spec_tpu.engine.registry import (
    ModelSpec,
    save_registry_entry,
)
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
from adversarial_spec_tpu.obs.events import validate_event

PARAMS = SamplingParams(max_new_tokens=8, greedy=True)


@pytest.fixture(autouse=True)
def _spec_off(monkeypatch):
    """Engine-seam tests: speculation only multiplies jit programs
    (the precedent of tests/test_tpu_engine.py's module fixture)."""
    from adversarial_spec_tpu.engine import spec as spec_mod

    prev = spec_mod.config()
    prev_enabled, prev_gamma = prev.enabled, prev.gamma
    monkeypatch.setenv("ADVSPEC_SPECULATIVE", "0")
    spec_mod.configure(enabled=False)
    yield
    spec_mod.configure(enabled=prev_enabled, gamma=prev_gamma)


def _req(model, user="hello"):
    return ChatRequest(model=model, system="sys", user=user)


# -- the ledger state machine ----------------------------------------------


class TestLedger:
    def test_load_demote_promote_free_conservation(self):
        led = weightres.WeightLedger(weightres.stats)
        led.admit_load("a", 100, 0.5)
        assert led.is_resident("a")
        led.demote_model("a", "payload", 50, 0.1)
        assert led.is_host("a")
        assert led.peek_host("a").payload == "payload"
        led.promote_model("a", 100, 0.05)
        assert led.is_resident("a")
        led.demote_model("a", "payload", 50)
        led.free_model("a")
        assert led.state("a") is None
        led.check_invariants()
        assert led.demoted == 2
        assert led.promoted == 1
        assert led.freed_host == 1

    def test_host_budget_overflow_frees_lru(self):
        led = weightres.WeightLedger(weightres.stats)
        for i, alias in enumerate(("a", "b", "c")):
            led.admit_load(alias, 100)
        # Budget fits two 50-byte host entries: the third demotion must
        # free the LRU host entry (a — demoted first, never touched).
        freed = []
        for alias in ("a", "b", "c"):
            freed += led.demote_model(
                alias, None, 50, host_budget_bytes=100
            )
        assert freed == ["a"]
        assert led.host_aliases() == ["b", "c"]
        led.check_invariants()

    def test_oversized_single_entry_freed(self):
        led = weightres.WeightLedger(weightres.stats)
        led.admit_load("big", 100)
        freed = led.demote_model("big", None, 500, host_budget_bytes=100)
        assert freed == ["big"]
        assert led.state("big") is None
        led.check_invariants()

    def test_pre_pin_merges_into_admission(self):
        led = weightres.WeightLedger(weightres.stats)
        led.acquire_weights("a")  # pinned before the load finishes
        led.admit_load("a", 10)
        assert led.pinned("a")
        assert led.lru_resident_alias() is None  # everything pinned
        led.release_weights("a")
        assert not led.pinned("a")
        assert led.lru_resident_alias() == "a"
        led.check_invariants()

    def test_swap_fault_leaves_host_entry(self):
        led = weightres.WeightLedger(weightres.stats)
        led.admit_load("a", 10)
        led.demote_model("a", "shards", 5)
        led.note_swap_fault("a")
        assert led.is_host("a")
        assert led.peek_host("a").payload == "shards"
        led.check_invariants()

    def test_double_publish_races_conserve(self):
        """Two racing loads (or promotions) of one alias both commit —
        the engine's ``_models`` dict tolerates the overwrite, so the
        ledger must too: the loser's admission retires the winner's
        through the surgery instead of double-counting it."""
        led = weightres.WeightLedger(weightres.stats)
        led.acquire_weights("a")
        led.admit_load("a", 10)
        led.admit_load("a", 10)  # racing loader published second
        assert led.is_resident("a")
        assert led.pinned("a")  # the pin survives the re-publication
        led.check_invariants()
        led.release_weights("a")
        led.demote_model("a", "shards", 5)
        # Both promoters passed peek_host before either committed.
        led.promote_model("a", 10)
        led.promote_model("a", 10)
        assert led.is_resident("a")
        led.check_invariants()
        assert led.promoted == 1  # one demotion, one promotion counted

    def test_clear_frees_everything(self):
        led = weightres.WeightLedger(weightres.stats)
        led.admit_load("a", 10)
        led.admit_load("b", 10)
        led.demote_model("a", None, 5)
        led.clear()
        assert led.resident_models == 0
        assert led.host_models == 0
        led.check_invariants()

    def test_fuzz_random_ops_conserve(self):
        """200 random walk steps over the machine: invariants hold
        after every transition."""
        import random

        rng = random.Random(15)
        led = weightres.WeightLedger(weightres.stats)
        aliases = [f"m{i}" for i in range(5)]
        for _ in range(200):
            alias = rng.choice(aliases)
            state = led.state(alias)
            op = rng.random()
            if state is None:
                led.admit_load(alias, rng.randrange(1, 100))
            elif state == weightres.RESIDENT:
                if op < 0.5:
                    led.demote_model(
                        alias, None, rng.randrange(1, 60),
                        host_budget_bytes=120,
                    )
                elif op < 0.7:
                    led.free_model(alias)
                else:
                    led.touch(alias)
            else:  # host
                if op < 0.5:
                    led.promote_model(alias, rng.randrange(1, 100))
                elif op < 0.7:
                    led.free_model(alias)
                else:
                    led.note_swap_fault(alias)
            led.check_invariants()

    def test_weight_events_validate(self):
        obs.reset_stats()
        led = weightres.WeightLedger(weightres.stats)
        led.admit_load("a", 10, 0.1)
        led.demote_model("a", None, 5, 0.01)
        led.promote_model("a", 10, 0.02)
        led.note_swap_fault("a")
        led.free_model("a")
        events = [
            e for e in obs.recorder.events() if e["type"] == "weight"
        ]
        assert [e["op"] for e in events] == [
            "load", "demote", "promote", "swap_fault", "free",
        ]
        for e in events:
            assert validate_event(e) == [], e
        # Post-op residency counts ride every event.
        assert events[1]["resident"] == 0 and events[1]["host"] == 1

    def test_snapshot_derived_fields(self):
        weightres.reset_stats()
        weightres.stats.loads = 1
        weightres.stats.load_s = 1.0
        weightres.stats.promotions = 3
        weightres.stats.promote_s = 0.5
        weightres.stats.promotions_overlapped = 2
        snap = weightres.snapshot()
        assert snap["weight_load_wall_s"] == 1.5
        assert snap["swap_overlap_fraction"] == round(2 / 3, 4)
        assert snap["reload_avoided_rate"] == 0.75
        assert snap["enabled"] is True  # config fields appended


class TestConfig:
    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("ADVSPEC_WEIGHT_RES", "0")
        assert weightres.env_enabled() is False
        monkeypatch.setenv("ADVSPEC_WEIGHT_HOST_MB", "123")
        assert weightres.env_host_mb() == 123
        monkeypatch.setenv("ADVSPEC_WEIGHT_HOST_MB", "garbage")
        assert weightres.env_host_mb() == weightres.DEFAULT_HOST_MB

    def test_paging_armed(self):
        weightres.configure(enabled=True, host_mb=0)
        assert not weightres.paging_armed()
        weightres.configure(enabled=False, host_mb=100)
        assert not weightres.paging_armed()
        weightres.configure(enabled=True, host_mb=100)
        assert weightres.paging_armed()

    def test_mock_budget_only_under_explicit_env(self, monkeypatch):
        assert weightres.mock_budget_bytes() is None
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", "1024")
        assert weightres.mock_budget_bytes() == 1024
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", "nope")
        assert weightres.mock_budget_bytes() is None


# -- mock-engine parity -----------------------------------------------------


class TestMockResidency:
    def _round(self, eng, n_models=4, rnd=1):
        from adversarial_spec_tpu.engine.mock import MockEngine  # noqa

        reqs = [
            _req(f"mock://critic?pool={m}", f"doc\nDebate round {rnd}")
            for m in range(n_models)
        ]
        return [c.text for c in eng.chat(reqs, SamplingParams())]

    def test_simulation_off_without_budget_env(self):
        from adversarial_spec_tpu.engine.mock import MockEngine

        eng = MockEngine()
        self._round(eng)
        assert eng.ledger is None
        assert weightres.stats.loads == 0

    def test_thrash_vs_resident_deterministic(self, monkeypatch):
        from adversarial_spec_tpu.engine import mock as mock_mod
        from adversarial_spec_tpu.engine.mock import MockEngine

        monkeypatch.setenv(
            "ADVSPEC_HBM_BUDGET_BYTES", str(2 * mock_mod._MODEL_BYTES)
        )

        def arm(paging):
            weightres.configure(enabled=paging, host_mb=1024)
            weightres.reset_stats()
            eng = MockEngine()
            texts = [self._round(eng, rnd=r) for r in (1, 2, 3, 4)]
            eng.ledger.check_invariants()
            return texts, weightres.snapshot()

        on_texts, on_snap = arm(True)
        off_texts, off_snap = arm(False)
        # Residency is accounting only: transcripts byte-identical.
        assert on_texts == off_texts
        # Paging on: 4 cold loads ever, swaps promote from host.
        assert on_snap["loads"] == 4
        assert on_snap["promotions"] == 6  # rounds 2-4: 2 swaps each
        assert on_snap["demotions"] == 8
        assert on_snap["swap_overlap_fraction"] == 1.0
        # Rounds 2 and 4 reorder ([2,3] resident); round 3's resident
        # set ({0,1} after round 2's swaps) already matches submission
        # order.
        assert on_snap["coalesced_groups"] == 2
        # Paging off: every swap re-loads, nothing promotes.
        assert off_snap["loads"] == 10
        assert off_snap["promotions"] == 0
        assert off_snap["freed_models"] == 8
        # The synthetic walls pin exactly (binary fractions) — and the
        # >=2x acceptance arithmetic holds on them.
        assert on_snap["weight_load_wall_s"] == 4 * 0.0625 + 6 * 0.0078125
        assert off_snap["weight_load_wall_s"] == 10 * 0.0625
        assert (
            off_snap["weight_load_wall_s"] / on_snap["weight_load_wall_s"]
            >= 2.0
        )

    def test_event_stream_byte_deterministic(self, monkeypatch):
        from adversarial_spec_tpu.engine import mock as mock_mod
        from adversarial_spec_tpu.engine.mock import MockEngine

        monkeypatch.setenv(
            "ADVSPEC_HBM_BUDGET_BYTES", str(2 * mock_mod._MODEL_BYTES)
        )

        def run():
            weightres.configure(enabled=True, host_mb=1024)
            weightres.reset_stats()
            obs.reset_stats()
            eng = MockEngine()
            for r in (1, 2):
                self._round(eng, rnd=r)
            return obs.recorder.to_jsonl()

        assert run() == run()
        jsonl = run()
        weight_lines = [
            json.loads(ln)
            for ln in jsonl.splitlines()
            if '"weight"' in ln
        ]
        assert any(e["op"] == "promote" for e in weight_lines)
        for e in weight_lines:
            assert validate_event(e) == [], e


# -- the real engine --------------------------------------------------------


class TestEngineResidency:
    def _load_bytes(self, eng, alias):
        return eng.ledger._entries[alias].bytes_device

    def test_demote_promote_byte_identical(self, monkeypatch):
        from adversarial_spec_tpu.engine.tpu import TpuEngine

        eng = TpuEngine()
        base = eng.chat([_req("tpu://random-tiny")], PARAMS)[0]
        one = self._load_bytes(eng, "random-tiny")
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        eng.chat([_req("tpu://random-mistral-tiny")], PARAMS)
        assert eng.ledger.is_host("random-tiny")
        assert eng.ledger.is_resident("random-mistral-tiny")
        assert "random-tiny" not in eng._models
        again = eng.chat([_req("tpu://random-tiny")], PARAMS)[0]
        assert again.text == base.text
        assert eng.ledger.is_host("random-mistral-tiny")
        eng.check_residency_invariants()
        assert weightres.stats.promotions >= 1

    def test_paging_off_frees_instead(self, monkeypatch):
        from adversarial_spec_tpu.engine.tpu import TpuEngine

        weightres.configure(enabled=False)
        eng = TpuEngine()
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        one = self._load_bytes(eng, "random-tiny")
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        eng.chat([_req("tpu://random-mistral-tiny")], PARAMS)
        assert eng.ledger.state("random-tiny") is None
        assert weightres.stats.freed_models == 1
        assert weightres.stats.demotions == 0
        eng.check_residency_invariants()

    def test_resident_first_group_order(self, monkeypatch):
        """A round whose group order would force an avoidable swap is
        reordered resident-first — and the reorder is counted."""
        from adversarial_spec_tpu.engine.tpu import TpuEngine

        eng = TpuEngine()
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        one = self._load_bytes(eng, "random-tiny")
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        eng.chat([_req("tpu://random-mistral-tiny")], PARAMS)
        # mistral is resident, tiny is host; a [tiny, mistral] round
        # must serve mistral first (no swap) and only then promote.
        served = []
        orig = TpuEngine._chat_one_model

        def spy(self, alias, *a, **k):
            served.append(alias)
            return orig(self, alias, *a, **k)

        monkeypatch.setattr(TpuEngine, "_chat_one_model", spy)
        before = weightres.stats.coalesced_groups
        comps = eng.chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert all(c.ok for c in comps)
        assert served == ["random-mistral-tiny", "random-tiny"]
        assert weightres.stats.coalesced_groups == before + 1

    def test_int4_model_serves_and_pages_quantized(self, monkeypatch):
        """Quantized resident checkpoints end to end: an int4-registered
        model serves through the ContinuousBatcher path with packed
        dict-leaf params, and its QUANTIZED shards are what demote to
        host and promote back — byte-identical transcripts across the
        round trip."""
        from adversarial_spec_tpu.engine.tpu import TpuEngine
        from adversarial_spec_tpu.ops.quant import is_quantized_int4

        save_registry_entry(
            ModelSpec(
                alias="res-int4-tiny", family="llama", size="tiny",
                dtype="float32", quant="int4", kv="paged", mesh={"dp": 1},
            )
        )
        eng = TpuEngine()
        base = eng.chat([_req("tpu://res-int4-tiny")], PARAMS)[0]
        assert base.ok, base.error
        lm = eng._models["res-int4-tiny"]
        assert is_quantized_int4(lm.params["layers"]["wq"])
        assert is_quantized_int4(lm.params["lm_head"])
        one = self._load_bytes(eng, "res-int4-tiny")
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        assert eng.ledger.is_host("res-int4-tiny")
        # The host tier holds the PACKED shards (demotion must not
        # dequantize): the payload's matmul weights are still int4
        # dict leaves, q4 half the contraction extent in int8.
        import numpy as np

        entry = eng.ledger.peek_host("res-int4-tiny")
        host_wq = entry.payload.np_params["layers"]["wq"]
        assert set(host_wq) == {"q4", "scale"}
        assert host_wq["q4"].dtype == np.int8
        again = eng.chat([_req("tpu://res-int4-tiny")], PARAMS)[0]
        assert again.text == base.text
        assert is_quantized_int4(
            eng._models["res-int4-tiny"].params["layers"]["wq"]
        )
        eng.check_residency_invariants()

    def test_no_leak_many_models_one_process(self, monkeypatch):
        """Satellite: a long-lived process cycling MANY models keeps a
        bounded resident set, a byte-bounded host tier, and drops every
        demoted model's batcher state with its weights."""
        import gc
        import weakref

        from adversarial_spec_tpu.engine.tpu import TpuEngine, hbm_budget_bytes

        aliases = []
        for i in range(6):
            alias = f"leak-{i}"
            save_registry_entry(
                ModelSpec(
                    alias=alias, family="llama", size="tiny",
                    dtype="float32", kv="paged", mesh={"dp": 1},
                )
            )
            aliases.append(alias)
        eng = TpuEngine()
        eng.chat([_req(f"tpu://{aliases[0]}")], PARAMS)
        one = self._load_bytes(eng, aliases[0])
        # Resident budget: 2 models; host budget: ~3 models' shards.
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 2.5)))
        host_mb = max(1, (3 * one) >> 20)
        weightres.configure(enabled=True, host_mb=host_mb)
        batcher_refs = []
        for alias in aliases:
            comps = eng.chat([_req(f"tpu://{alias}")], PARAMS)
            assert comps[0].ok, comps[0].error
            lm = eng._models.get(alias)
            if lm is not None and lm.batcher is not None:
                batcher_refs.append(weakref.ref(lm.batcher))
        eng.check_residency_invariants()
        # Resident set bounded by the byte budget.
        resident = sum(
            m.bytes_per_chip for m in eng._models.values()
        )
        assert resident <= hbm_budget_bytes()
        assert eng.ledger.resident_models <= 2
        # Host tier byte-bounded: overflow freed, not accumulated.
        assert eng.ledger.host_bytes <= host_mb << 20
        assert weightres.stats.freed_models > 0
        # Demoted models' batchers (page pools = HBM) are collectable:
        # only still-resident models may hold one.
        gc.collect()
        live = sum(1 for r in batcher_refs if r() is not None)
        assert live <= len(eng._models), (
            f"{live} batchers alive for {len(eng._models)} resident "
            "models — demotion leaked batcher state"
        )

    def test_repromotion_zero_unexpected_recompiles(self, monkeypatch):
        """The committed-sharding discipline applied to params: a
        promoted model's arrays restore their original shardings, so
        the SAME jit programs serve them — the retrace watch must see
        zero unexpected recompiles across demote → promote → serve."""
        from adversarial_spec_tpu.engine.tpu import TpuEngine

        save_registry_entry(
            ModelSpec(alias="cont-tiny", family="llama", size="tiny",
                      kv="paged", dtype="float32", mesh={"dp": 1})
        )
        eng = TpuEngine()
        base = eng.chat([_req("tpu://cont-tiny", "alpha beta")], PARAMS)
        assert base[0].ok, base[0].error
        one = self._load_bytes(eng, "cont-tiny")
        # random-tiny is bf16 (half the f32 bytes): 1.2x leaves no room
        # for even the half-size newcomer beside cont-tiny.
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.2)))
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        assert eng.ledger.is_host("cont-tiny")
        # Everything is compiled now; a re-promotion must add nothing.
        obs.retrace.clear()
        again = eng.chat([_req("tpu://cont-tiny", "alpha beta")], PARAMS)
        assert again[0].ok and again[0].text == base[0].text
        snap = obs.retrace.snapshot()
        assert snap["unexpected_recompiles"] == 0, snap

    @pytest.mark.chaos
    def test_swap_fault_evicts_only_waiting_admission(
        self, monkeypatch, tmp_path
    ):
        """A fault mid-promotion degrades ONLY the group waiting on the
        swap; the ledger stays conservation-clean with the victim still
        host-resident, and the autodump reconstructs the failed swap."""
        from adversarial_spec_tpu.engine.tpu import TpuEngine
        from adversarial_spec_tpu.resilience import injector

        events_out = tmp_path / "ev.jsonl"
        obs.configure(enabled=True, events_out=str(events_out))
        eng = TpuEngine()
        eng.chat([_req("tpu://random-tiny")], PARAMS)
        one = self._load_bytes(eng, "random-tiny")
        monkeypatch.setenv("ADVSPEC_HBM_BUDGET_BYTES", str(int(one * 1.5)))
        base = eng.chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert all(c.ok for c in base)
        victim = next(
            a for a in ("random-tiny", "random-mistral-tiny")
            if eng.ledger.is_host(a)
        )
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec("oom@weight_swap:times=1")
            )
        )
        try:
            comps = eng.chat(
                [
                    _req("tpu://random-tiny"),
                    _req("tpu://random-mistral-tiny"),
                ],
                PARAMS,
            )
        finally:
            injector.install(None)
        by_model = {
            "random-tiny": comps[0],
            "random-mistral-tiny": comps[1],
        }
        assert not by_model[victim].ok
        assert by_model[victim].transient
        other = next(a for a in by_model if a != victim)
        assert by_model[other].ok, by_model[other].error
        assert eng.ledger.is_host(victim)
        eng.check_residency_invariants()
        assert weightres.stats.swap_faults == 1
        dump = tmp_path / "ev.fault.jsonl"
        assert dump.exists()
        lines = [
            json.loads(ln)
            for ln in dump.read_text().splitlines()
            if ln
        ]
        for ln in lines:
            assert validate_event(ln) == [], ln
        faults = [e for e in lines if e["type"] == "fault"]
        swap_faults = [
            e
            for e in lines
            if e["type"] == "weight" and e["op"] == "swap_fault"
        ]
        assert faults and swap_faults
        assert swap_faults[-1]["alias"] == victim
        # The retry round heals: same shards promote byte-identically.
        again = eng.chat(
            [_req("tpu://random-tiny"), _req("tpu://random-mistral-tiny")],
            PARAMS,
        )
        assert [c.text for c in again] == [c.text for c in base]
        eng.check_residency_invariants()


# -- serve-scheduler coalescing --------------------------------------------


class TestServeCoalesce:
    def _unit(self, model, engine, tenant="t", debate="d", index=0):
        from adversarial_spec_tpu.serve.sched import Unit

        return Unit(
            tenant=tenant,
            tier="interactive",
            debate=debate,
            index=index,
            engine=engine,
            request=_req(model),
            params=SamplingParams(),
        )

    def test_same_model_pulled_ahead_of_swap(self):
        from adversarial_spec_tpu import serve as serve_mod
        from adversarial_spec_tpu.serve.sched import ServeScheduler

        serve_mod.configure(max_dispatch_batch=4)
        eng = object()
        sched = ServeScheduler()
        sched.submit_units(
            [
                self._unit("mock://m1", eng, index=0),
                self._unit("mock://m2", eng, index=1),
                self._unit("mock://m1", eng, index=2),
            ]
        )
        before = weightres.stats.coalesced_units
        batch = sched.next_batch(timeout=0.01)
        # m1's two units coalesce into one dispatch; the m2 swap waits.
        assert [u.request.model for u in batch] == [
            "mock://m1",
            "mock://m1",
        ]
        assert weightres.stats.coalesced_units == before + 1
        nxt = sched.next_batch(timeout=0.01)
        assert [u.request.model for u in nxt] == ["mock://m2"]

    def test_steal_disabled_with_weightres_off(self):
        from adversarial_spec_tpu import serve as serve_mod
        from adversarial_spec_tpu.serve.sched import ServeScheduler

        weightres.configure(enabled=False)
        serve_mod.configure(max_dispatch_batch=4)
        eng = object()
        sched = ServeScheduler()
        sched.submit_units(
            [
                self._unit("mock://m1", eng, index=0),
                self._unit("mock://m2", eng, index=1),
                self._unit("mock://m1", eng, index=2),
            ]
        )
        batch = sched.next_batch(timeout=0.01)
        assert [u.request.model for u in batch] == ["mock://m1"]


# -- CLI plumbing -----------------------------------------------------------


class TestCliWeights:
    def _run(self, monkeypatch, capsys, extra=()):
        import io
        import sys as _sys

        from adversarial_spec_tpu.cli import main as cli_main

        monkeypatch.setattr(
            _sys, "stdin", io.StringIO("## Spec\nA tiny spec.\n")
        )
        rc = cli_main(
            [
                "critique",
                "--models",
                "mock://agree",
                "--json",
                *extra,
            ]
        )
        assert rc == 0
        return json.loads(capsys.readouterr().out)

    def test_perf_weights_block(self, monkeypatch, capsys):
        out = self._run(monkeypatch, capsys)
        weights = out["perf"]["weights"]
        assert weights["enabled"] is True
        assert weights["host_mb"] == weightres.DEFAULT_HOST_MB
        for key in (
            "loads",
            "promotions",
            "demotions",
            "swap_overlap_fraction",
            "weight_load_wall_s",
            "coalesced_units",
        ):
            assert key in weights

    def test_flags_and_no_leak(self, monkeypatch, capsys):
        out = self._run(
            monkeypatch,
            capsys,
            extra=["--no-weight-res", "--weight-host-mb", "64"],
        )
        assert out["perf"]["weights"]["enabled"] is False
        assert out["perf"]["weights"]["host_mb"] == 64
        # Next invocation re-resolves to env defaults: no leak.
        out = self._run(monkeypatch, capsys)
        assert out["perf"]["weights"]["enabled"] is True
        assert (
            out["perf"]["weights"]["host_mb"] == weightres.DEFAULT_HOST_MB
        )

    def test_env_defaults(self, monkeypatch, capsys):
        monkeypatch.setenv("ADVSPEC_WEIGHT_RES", "0")
        monkeypatch.setenv("ADVSPEC_WEIGHT_HOST_MB", "96")
        out = self._run(monkeypatch, capsys)
        assert out["perf"]["weights"]["enabled"] is False
        assert out["perf"]["weights"]["host_mb"] == 96


# -- tools: obs_dump + bench_trend -----------------------------------------


class TestTools:
    def test_obs_dump_renders_weight_rows(self):
        from tools.obs_dump import occupancy_timeline, summarize

        obs.reset_stats()
        led = weightres.WeightLedger(weightres.stats)
        obs.emit(obs.StepEvent(kind="decode", n_live=1))
        led.admit_load("m1", 64 << 20, 0.5)
        led.demote_model("m1", None, 32 << 20, 0.01)
        led.note_swap_fault("m1")
        events = obs.recorder.events()
        timeline = occupancy_timeline(events)
        assert "w:load" in timeline and "w:demote" in timeline
        assert "w:swap_fault" in timeline
        assert "res=" in timeline and "host=" in timeline
        summary = summarize(events)
        assert "weight residency:" in summary
        assert "swap(s) aborted" in summary

    def test_bench_trend_validates_residency_schema(self, tmp_path):
        from tools.bench_trend import validate_bench_file

        good = {
            "metric": "residency_load_wall_ratio",
            "value": 2.5,
            "unit": "x",
            "platform": "cpu",
            "load_wall_resident_s": 0.1,
            "load_wall_thrash_s": 0.25,
            "swap_overlap_fraction": 1.0,
            "transcripts_byte_identical": {"mock": True, "real": True},
            "unexpected_recompiles": 0,
        }
        p = tmp_path / "BENCH_residency.json"
        p.write_text(json.dumps(good))
        row, problems = validate_bench_file(p)
        assert problems == [] and row is not None
        # Missing a pinned field = violation.
        bad = dict(good)
        del bad["swap_overlap_fraction"]
        p.write_text(json.dumps(bad))
        _, problems = validate_bench_file(p)
        assert problems
        # A false transcript arm = violation.
        bad = dict(good)
        bad["transcripts_byte_identical"] = {"mock": True, "real": False}
        p.write_text(json.dumps(bad))
        _, problems = validate_bench_file(p)
        assert any("false arm" in x for x in problems)

    def test_committed_bench_residency_valid(self):
        from pathlib import Path

        from tools.bench_trend import validate_bench_file

        path = (
            Path(__file__).resolve().parent.parent
            / "BENCH_residency.json"
        )
        row, problems = validate_bench_file(path)
        assert problems == []
        assert row["mode"] == "residency"


# -- graftlint registrations ------------------------------------------------


class TestGraftlintWeightres:
    def test_lifecycle_live_fire_pin(self):
        """Stripping the ledger's release surgery fires GL-LIFECYCLE on
        the real source — the fourth machine is live, not decorative."""
        from pathlib import Path

        from tools.graftlint.core import lint_sources

        path = "adversarial_spec_tpu/engine/weightres.py"
        src = (Path(__file__).resolve().parent.parent / path).read_text()
        assert lint_sources({path: src}, rules=["GL-LIFECYCLE"]) == []
        assert "self._retire_model(" in src
        mutated = src.replace(
            "self._retire_model(", "(lambda *a, **k: None)("
        )
        findings = lint_sources({path: mutated}, rules=["GL-LIFECYCLE"])
        assert findings, (
            "stripping _retire_model produced no GL-LIFECYCLE finding "
            "— the weightres machine is unguarded"
        )
        assert "WeightLedger" in " ".join(f.message for f in findings)

    def test_refcount_pair_live(self):
        """An acquire_weights with no covering release fires
        GL-REFCOUNT — the residency pin pair is enforced, and the real
        tpu.py call site is clean."""
        from pathlib import Path

        from tools.graftlint.core import lint_sources

        leaky = (
            "def serve(ledger, alias, chat):\n"
            "    ledger.acquire_weights(alias)\n"
            "    result = chat(alias)  # can raise: pin leaks\n"
            "    ledger.release_weights(alias)\n"
            "    return result\n"
        )
        from tools.graftlint.config import GraftlintConfig

        cfg = GraftlintConfig()
        cfg.refcount_modules = ["pkg.leaky"]
        findings = lint_sources(
            {"pkg/leaky.py": leaky}, rules=["GL-REFCOUNT"], cfg=cfg
        )
        assert any("acquire_weights" in f.message for f in findings)
        real = "adversarial_spec_tpu/engine/tpu.py"
        src = (Path(__file__).resolve().parent.parent / real).read_text()
        assert "acquire_weights(" in src
        assert (
            lint_sources({real: src}, rules=["GL-REFCOUNT"]) == []
        )

    def test_dequant_helpers_are_traced_roots(self):
        """Satellite pin: the int4/int8 dequant helpers are reached by
        GL-TRACE's jit-root closure (they trace into the forwards, so
        an impure call added to them would be caught)."""
        from pathlib import Path

        from tools.graftlint.config import load_config
        from tools.graftlint.core import (
            DEFAULT_ROOTS,
            Context,
            build_index,
            collect_files,
        )
        from tools.graftlint.rules.trace import traced_functions

        repo = Path(__file__).resolve().parent.parent
        cfg = load_config(repo)
        files = collect_files([repo / r for r in DEFAULT_ROOTS])
        index = build_index(
            files, repo, set(cfg.sig_preserving_decorators)
        )
        ctx = Context(repo, cfg, index)
        quant_roots = {
            fn
            for (mod, fn) in traced_functions(ctx)
            if mod.endswith("ops.quant")
        }
        assert "matmul" in quant_roots
        assert "unpack_int4" in quant_roots
