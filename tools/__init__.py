"""Operational tools: coverage gate, TPU-harvest analysis, compile checks."""
