"""Thin compat entrypoint over tools/graftlint (the framework this
script grew into — see docs/static_analysis.md).

``python tools/astlint.py [roots...]`` runs exactly the four checks the
original flat script shipped, now as registered graftlint rules:

1. bad from-imports            -> GL-IMPORT
2. bad module-attribute access -> GL-ATTR
3. call arity / keywords       -> GL-ARITY
4. scheduler sync discipline   -> GL-SYNC (generalized: the original
   only caught explicit ``jax.block_until_ready``; GL-SYNC also catches
   the implicit syncs — np.asarray / .item() / int()/bool() /
   device_get / truthiness on device values)

The hardcoded ``_SCHEDULER_SYNC_ALLOWLIST`` / ``_SIG_PRESERVING`` sets
moved to the ``[tool.graftlint]`` table in pyproject.toml. Output and
exit-code behavior are preserved: findings on stdout, an
"astlint: N finding(s) over M files (K call sites arity-checked)"
summary on stderr, exit 1 iff findings.

For the full rule set (GL-TRACE, GL-RETRACE, GL-REFCOUNT, …),
suppressions, baselines and JSON output use ``python -m tools.graftlint``.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

LEGACY_RULES = ["GL-IMPORT", "GL-ATTR", "GL-ARITY", "GL-SYNC"]


def main(argv: list[str]) -> int:
    from tools.graftlint import core

    try:
        result = core.run(argv or None, rules=LEGACY_RULES)
    except SyntaxError as e:
        print(f"syntax error: {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 1
    for f in result.findings:
        print(f.render())
    print(
        f"astlint: {len(result.findings)} finding(s) over "
        f"{result.n_files} files "
        f"({result.n_checked_calls} call sites arity-checked)",
        file=sys.stderr,
    )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
