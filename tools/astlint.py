"""Stdlib static checker for the worst type-error classes (mypy is not
installable in this environment; reference CI runs a real typecheck job —
reference .github/workflows/ci.yml — and this is the executable stand-in).

Checks, package-wide (no third-party deps, pure ast):

1. ``from <package>.<module> import NAME`` — NAME must actually be bound
   in the target module (def / class / assignment / re-export / __all__).
2. ``<module>.NAME`` attribute access on package modules imported as a
   module object — NAME must be bound in that module.
3. Call arity + keyword validity for calls that statically resolve to a
   function, class constructor, or ``self.method`` defined in this
   package: not enough / too many positional args, unknown keyword args,
   missing required keyword-only args.
4. Scheduler sync discipline: ``jax.block_until_ready`` may not appear
   inside ``ContinuousBatcher`` outside the allowlisted sanctioned sync
   points (``_SCHEDULER_SYNC_ALLOWLIST``). The pipelined drive loop's
   whole point is that the host never blanket-syncs between chunks —
   this rule keeps the stall from silently creeping back in a refactor.

Deliberately conservative: calls through *args/**kwargs, decorated
functions whose decorator is not known signature-preserving, attribute
chains through values, and anything not statically resolvable are
skipped. Zero output = clean. Exit 1 on findings, 0 otherwise.

Usage:
    python tools/astlint.py                # lint the package + tools
    python tools/astlint.py path1 path2    # explicit roots
"""

from __future__ import annotations

import ast
import sys
from dataclasses import dataclass, field
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = "adversarial_spec_tpu"

# Decorators that keep the wrapped function's calling convention.
_SIG_PRESERVING = {
    "jax.jit",
    "jit",
    "functools.lru_cache",
    "lru_cache",
    "functools.cache",
    "functools.wraps",
    "staticmethod",
    "classmethod",
    "contextmanager",
    "contextlib.contextmanager",
    "dataclass",
    "dataclasses.dataclass",
    "abstractmethod",
    "abc.abstractmethod",
    "pytest.fixture",
    "override",
}
# functools.partial(jax.jit, static_argnames=...) — the common jit idiom
# here — also preserves the wrapped signature for callers.

# ContinuousBatcher methods allowed to call jax.block_until_ready: the
# standalone (stalled) admission chunk — blocked deliberately so its
# device time is billed to the newcomer, not the next decode chunk — and
# the legacy serialized loop kept as the --no-interleave escape hatch.
# Everything else must use targeted fetches (np.asarray / device_get on
# the specific small arrays) at the sanctioned sync points only.
_SCHEDULER_SYNC_CLASS = "ContinuousBatcher"
_SCHEDULER_SYNC_ALLOWLIST = {"_advance_admission", "_drive_legacy"}


@dataclass
class FuncSig:
    name: str
    n_pos: int  # positional (posonly + args), excluding self for methods
    n_pos_defaults: int
    kwonly: tuple[str, ...] = ()
    kwonly_required: tuple[str, ...] = ()
    has_vararg: bool = False
    has_kwarg: bool = False
    pos_names: tuple[str, ...] = ()
    checkable: bool = True  # False when a decorator may change the sig


@dataclass
class ClassInfo:
    name: str
    methods: dict[str, FuncSig] = field(default_factory=dict)
    bases: tuple[str, ...] = ()


@dataclass
class ModuleInfo:
    path: Path
    modname: str
    bindings: set[str] = field(default_factory=set)
    functions: dict[str, FuncSig] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)


def _decorator_name(dec: ast.expr) -> str:
    if isinstance(dec, ast.Call):
        # functools.partial(jax.jit, ...) preserves the signature; any
        # other called decorator factory is treated as preserving too iff
        # its name is in the allowlist (e.g. lru_cache(maxsize=...)).
        inner = _decorator_name(dec.func)
        if inner in ("functools.partial", "partial"):
            if dec.args:
                wrapped = _decorator_name(dec.args[0])
                if wrapped in _SIG_PRESERVING:
                    return wrapped
            return "partial(?)"
        return inner
    if isinstance(dec, ast.Attribute):
        base = _decorator_name(dec.value)
        return f"{base}.{dec.attr}" if base else dec.attr
    if isinstance(dec, ast.Name):
        return dec.id
    return "?"


def _sig_of(fn: ast.FunctionDef | ast.AsyncFunctionDef, is_method: bool) -> FuncSig:
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    skip_self = 0
    if is_method:
        decs = {_decorator_name(d) for d in fn.decorator_list}
        if "staticmethod" not in decs and pos:
            skip_self = 1  # self / cls
    pos = pos[skip_self:]
    checkable = True
    for d in fn.decorator_list:
        name = _decorator_name(d)
        if name not in _SIG_PRESERVING and not name.startswith(
            ("jax.", "functools.", "pl.", "pytest.")
        ):
            checkable = False
    kwonly = tuple(p.arg for p in a.kwonlyargs)
    kwonly_required = tuple(
        p.arg
        for p, d in zip(a.kwonlyargs, a.kw_defaults)
        if d is None
    )
    return FuncSig(
        name=fn.name,
        n_pos=len(pos),
        n_pos_defaults=len(a.defaults),
        kwonly=kwonly,
        kwonly_required=kwonly_required,
        has_vararg=a.vararg is not None,
        has_kwarg=a.kwarg is not None,
        pos_names=tuple(pos),
        checkable=checkable,
    )


def _collect_module(path: Path, modname: str) -> ModuleInfo:
    tree = ast.parse(path.read_text(encoding="utf-8"))
    info = ModuleInfo(path=path, modname=modname)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.bindings.add(node.name)
            info.functions[node.name] = _sig_of(node, is_method=False)
        elif isinstance(node, ast.ClassDef):
            info.bindings.add(node.name)
            ci = ClassInfo(
                name=node.name,
                bases=tuple(
                    _decorator_name(b)
                    for b in node.bases
                ),
            )
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = _sig_of(sub, is_method=True)
            info.classes[node.name] = ci
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    info.bindings.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            info.bindings.add(e.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            info.bindings.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                info.bindings.add(
                    alias.asname or alias.name.split(".")[0]
                )
        elif isinstance(node, (ast.If, ast.Try)):
            # Conditional top-level defs (TYPE_CHECKING, fallbacks):
            # bind anything defined in any branch.
            for sub in ast.walk(node):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    info.bindings.add(sub.name)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            info.bindings.add(t.id)
                elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        if alias.name != "*":
                            info.bindings.add(
                                alias.asname or alias.name.split(".")[0]
                            )
    return info


def _modname_for(path: Path) -> str:
    rel = path.relative_to(REPO).with_suffix("")
    parts = list(rel.parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _Checker(ast.NodeVisitor):
    def __init__(
        self,
        info: ModuleInfo,
        index: dict[str, ModuleInfo],
        findings: list[str],
    ):
        self.info = info
        self.index = index
        self.findings = findings
        # local name -> ("func", FuncSig) | ("class", ClassInfo)
        #            | ("module", ModuleInfo)
        self.resolved: dict[str, tuple[str, object]] = {}
        self.local_overrides: set[str] = set()
        self.current_class: ClassInfo | None = None
        for name, sig in info.functions.items():
            self.resolved[name] = ("func", sig)
        for name, ci in info.classes.items():
            self.resolved[name] = ("class", ci)

    def _warn(self, node: ast.AST, msg: str) -> None:
        rel = self.info.path.relative_to(REPO)
        self.findings.append(f"{rel}:{node.lineno}: {msg}")

    # ---------------------------------------------------------- imports

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level:
            # Level 1 means "this package": for a package __init__ that is
            # the module itself; for a plain module it is the parent.
            drop = node.level - (
                1 if self.info.path.name == "__init__.py" else 0
            )
            base = (
                self.info.modname
                if drop == 0
                else self.info.modname.rsplit(".", drop)[0]
            )
            target = f"{base}.{node.module}" if node.module else base
        else:
            target = node.module or ""
        tinfo = self.index.get(target)
        if tinfo is not None:
            for alias in node.names:
                if alias.name == "*":
                    continue
                # Submodule import (from pkg import engine) counts.
                if (
                    alias.name not in tinfo.bindings
                    and f"{target}.{alias.name}" not in self.index
                ):
                    self._warn(
                        node,
                        f"'{alias.name}' is not defined in {target}",
                    )
                local = alias.asname or alias.name
                if alias.name in tinfo.functions:
                    self.resolved[local] = (
                        "func",
                        tinfo.functions[alias.name],
                    )
                elif alias.name in tinfo.classes:
                    self.resolved[local] = (
                        "class",
                        tinfo.classes[alias.name],
                    )
                elif f"{target}.{alias.name}" in self.index:
                    self.resolved[local] = (
                        "module",
                        self.index[f"{target}.{alias.name}"],
                    )
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name in self.index:
                local = alias.asname or alias.name.split(".")[0]
                if alias.asname or "." not in alias.name:
                    self.resolved[local] = (
                        "module",
                        self.index[alias.name],
                    )
        self.generic_visit(node)

    # ------------------------------------------------------ assignments

    def visit_Assign(self, node: ast.Assign) -> None:
        # A local rebind shadows whatever we resolved — stop checking it.
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in self.resolved:
                self.resolved.pop(t.id, None)
        self.generic_visit(node)

    # ---------------------------------------------------------- classes

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self.current_class
        self.current_class = self.info.classes.get(node.name)
        self.generic_visit(node)
        self.current_class = prev

    # ------------------------------------------------------------ scopes

    def _shadowed_names(self, fn) -> set[str]:
        """Names this function rebinds locally: params plus local
        assignment/for/with/except targets (one level of flow analysis —
        enough to avoid false positives, not a full scope model)."""
        names = set()
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            names.add(p.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        return names

    def _visit_function_scope(self, node) -> None:
        shadowed = {
            n: self.resolved.pop(n)
            for n in self._shadowed_names(node)
            if n in self.resolved
        }
        self.generic_visit(node)
        self.resolved.update(shadowed)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function_scope(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function_scope(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_function_scope(node)

    # ------------------------------------------------------- attributes

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name):
            entry = self.resolved.get(node.value.id)
            if entry and entry[0] == "module":
                minfo: ModuleInfo = entry[1]  # type: ignore[assignment]
                if (
                    node.attr not in minfo.bindings
                    and f"{minfo.modname}.{node.attr}" not in self.index
                    and not node.attr.startswith("__")
                ):
                    self._warn(
                        node,
                        f"module '{minfo.modname}' has no attribute "
                        f"'{node.attr}'",
                    )
        self.generic_visit(node)

    # ------------------------------------------------------------ calls

    n_checked_calls = 0  # class-wide: how many call sites were verified

    def _check_sig(
        self, node: ast.Call, sig: FuncSig, what: str
    ) -> None:
        if not sig.checkable:
            return
        if any(isinstance(a, ast.Starred) for a in node.args) or any(
            kw.arg is None for kw in node.keywords
        ):
            return  # *args / **kwargs at the call site: not statically known
        _Checker.n_checked_calls += 1
        n_pos_given = len(node.args)
        kw_given = {kw.arg for kw in node.keywords}
        # positional overflow
        if not sig.has_vararg and n_pos_given > sig.n_pos:
            self._warn(
                node,
                f"{what} takes {sig.n_pos} positional args "
                f"but {n_pos_given} given",
            )
            return
        # unknown keywords
        if not sig.has_kwarg:
            valid = set(sig.pos_names) | set(sig.kwonly)
            for kw in kw_given:
                if kw not in valid:
                    self._warn(
                        node, f"{what} got unexpected keyword '{kw}'"
                    )
        # missing required args: only keywords naming a REQUIRED
        # positional cover one (a keyword hitting an optional positional
        # must not mask a missing required arg, e.g. f(b=2) on f(a, b=1)).
        required_pos = sig.n_pos - sig.n_pos_defaults
        covered = n_pos_given + len(
            kw_given & set(sig.pos_names[n_pos_given:required_pos])
        )
        if covered < required_pos:
            self._warn(
                node,
                f"{what} missing required args "
                f"({covered} of {required_pos} provided)",
            )
        for kw in sig.kwonly_required:
            if kw not in kw_given:
                self._warn(
                    node, f"{what} missing required keyword-only '{kw}'"
                )

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name):
            entry = self.resolved.get(func.id)
            if entry:
                kind, obj = entry
                if kind == "func":
                    self._check_sig(node, obj, f"{func.id}()")
                elif kind == "class":
                    ci: ClassInfo = obj  # type: ignore[assignment]
                    init = ci.methods.get("__init__")
                    # dataclasses synthesize __init__; bases may define
                    # it — only check an explicit local __init__.
                    if init is not None and not ci.bases:
                        self._check_sig(node, init, f"{ci.name}()")
        elif isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and self.current_class is not None
            ):
                sig = self.current_class.methods.get(func.attr)
                # Inherited methods not indexed: only check when the
                # class has no bases or defines the method itself.
                if sig is not None:
                    self._check_sig(
                        node,
                        sig,
                        f"self.{func.attr}()",
                    )
            elif isinstance(func.value, ast.Name):
                entry = self.resolved.get(func.value.id)
                if entry and entry[0] == "module":
                    minfo: ModuleInfo = entry[1]  # type: ignore
                    sig = minfo.functions.get(func.attr)
                    if sig is not None:
                        self._check_sig(
                            node,
                            sig,
                            f"{minfo.modname}.{func.attr}()",
                        )
        self.generic_visit(node)


def _is_block_until_ready(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
        return True
    return isinstance(f, ast.Name) and f.id == "block_until_ready"


def check_scheduler_sync(index: dict[str, ModuleInfo], findings: list[str]) -> None:
    """Rule 4: no blanket device sync inside the continuous batcher
    outside the allowlisted sanctioned sync points."""
    info = index.get(f"{PACKAGE}.engine.scheduler")
    if info is None:
        return
    tree = ast.parse(info.path.read_text(encoding="utf-8"))
    for node in tree.body:
        if (
            not isinstance(node, ast.ClassDef)
            or node.name != _SCHEDULER_SYNC_CLASS
        ):
            continue
        for method in node.body:
            if not isinstance(
                method, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if method.name in _SCHEDULER_SYNC_ALLOWLIST:
                continue
            for sub in ast.walk(method):
                if isinstance(sub, ast.Call) and _is_block_until_ready(sub):
                    rel = info.path.relative_to(REPO)
                    findings.append(
                        f"{rel}:{sub.lineno}: jax.block_until_ready in "
                        f"{_SCHEDULER_SYNC_CLASS}.{method.name} — not an "
                        "allowlisted sync point "
                        f"({', '.join(sorted(_SCHEDULER_SYNC_ALLOWLIST))}); "
                        "use a targeted fetch at a sanctioned sync point "
                        "or extend _SCHEDULER_SYNC_ALLOWLIST deliberately"
                    )


def main(argv: list[str]) -> int:
    roots = [Path(p).resolve() for p in argv] or [
        REPO / PACKAGE,
        REPO / "tools",
        REPO / "tests",
        REPO / "bench.py",
        REPO / "__graft_entry__.py",
        REPO / "tpu_ladder.py",
    ]
    files: list[Path] = []
    for r in roots:
        if r.is_dir():
            files += sorted(r.rglob("*.py"))
        elif r.suffix == ".py" and r.exists():
            files.append(r)

    index: dict[str, ModuleInfo] = {}
    for f in files:
        try:
            index[_modname_for(f)] = _collect_module(f, _modname_for(f))
        except SyntaxError as e:
            print(f"{f}: syntax error: {e}", file=sys.stderr)
            return 1

    findings: list[str] = []
    for modname, info in index.items():
        _Checker(info, index, findings).visit(
            ast.parse(info.path.read_text(encoding="utf-8"))
        )
    check_scheduler_sync(index, findings)

    for f in findings:
        print(f)
    n_files = len(files)
    print(
        f"astlint: {len(findings)} finding(s) over {n_files} files "
        f"({_Checker.n_checked_calls} call sites arity-checked)",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
