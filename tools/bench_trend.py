"""Aggregate every committed ``BENCH_*.json`` into one perf-trajectory
table.

Eight PRs in, the bench record is scattered across per-mode files
(``BENCH_prefix.json``, ``BENCH_obs.json``, …) and per-run ladder
wrappers (``BENCH_r01.json``'s ``{n, cmd, rc, tail, parsed}``) that
nobody joins — this tool is the join: one row per file with the mode,
headline metric, value/unit, platform, and budget verdict, so a
reviewer reads the whole perf trajectory at a glance and a regression
(or a silently invalid bench file) can't hide in a file nobody opens.

Every file is SCHEMA-VALIDATED first: metric-style payloads must carry
``metric``/``value``/``unit``/``platform`` with the right types; ladder
wrappers must carry ``n``/``cmd``/``rc`` and, when the wrapped run
succeeded, a ``parsed`` metric payload. A violation is a nonzero exit —
``tools/lint_all.py --full`` runs this, so a malformed bench file fails
the preflight gate instead of silently dropping out of the record.

Usage:
    python tools/bench_trend.py            # table over repo-root BENCH_*
    python tools/bench_trend.py --json     # machine-readable rows
    python tools/bench_trend.py --dir D    # another directory

Exit codes: 0 = all files valid; 1 = schema violations; 2 = no bench
files found / unreadable directory.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Metric-style payload contract (bench.py's output schema): field ->
# required type(s). ``vs_baseline`` may be None (budget pins).
_METRIC_REQUIRED: dict[str, tuple[type, ...]] = {
    "metric": (str,),
    "value": (int, float),
    "unit": (str,),
    "platform": (str,),
}
# Ladder wrapper contract (tpu_session.sh round files).
_LADDER_REQUIRED: dict[str, tuple[type, ...]] = {
    "n": (int,),
    "cmd": (str,),
    "rc": (int,),
}
# BENCH_serve.json additionally pins the serving trajectory: the shed
# fraction at the overload point, the brownout transition count, and
# the capacity point the admission caps are sized against — a serve
# bench that silently dropped one of these would hide a capacity
# regression behind a still-valid headline metric.
_SERVE_REQUIRED: dict[str, tuple[type, ...]] = {
    "shed_fraction": (int, float),
    "brownout_transitions": (int,),
    "capacity": (dict,),
}
# BENCH_residency.json additionally pins the weight-paging trajectory:
# total weight-load seconds resident-vs-thrash (the >=2x headline), the
# swap-overlap fraction (promotions that rode another model's decode),
# byte-identical transcripts across arms, and zero unexpected
# recompiles on re-promotion — a residency bench silently dropping one
# of these would hide a paging regression behind a valid headline.
_RESIDENCY_REQUIRED: dict[str, tuple[type, ...]] = {
    "load_wall_resident_s": (int, float),
    "load_wall_thrash_s": (int, float),
    "swap_overlap_fraction": (int, float),
    "transcripts_byte_identical": (dict,),
    "unexpected_recompiles": (int,),
}
# BENCH_elastic.json additionally pins the elasticity trajectory: the
# accepted-debate throughput of both load-step arms (the >1x headline
# must stay decomposable), interactive p99 TTFT per arm (growth must
# not trade admission for latency collapse), byte-identical transcripts
# across the planned scale-in, and zero duplicated completions — an
# elastic bench silently dropping one of these would hide a membership-
# change regression behind a valid headline ratio.
_ELASTIC_REQUIRED: dict[str, tuple[type, ...]] = {
    "accepted_throughput_elastic": (int, float),
    "accepted_throughput_fixed": (int, float),
    "ttft_p99_s": (dict,),
    "transcripts_byte_identical": (dict,),
    "duplicated_completions": (int,),
}
# BENCH_disagg.json additionally pins the disaggregation trajectory:
# decode-side p99 TTFT per arm (the headline speedup must stay
# decomposable), accepted-debate throughput per arm, the cross-replica
# KV handoff hit fraction (a disagg bench whose handoffs silently all
# degraded to local prefill would report a meaningless TTFT win),
# byte-identical transcripts disagg-vs-symmetric, zero duplicated
# completions, and zero decode-side unexpected recompiles.
_DISAGG_REQUIRED: dict[str, tuple[type, ...]] = {
    "ttft_p99_s": (dict,),
    "accepted_debates_per_s": (dict,),
    "handoff_hit_fraction": (int, float),
    "handoff": (dict,),
    "transcripts_byte_identical": (dict,),
    "duplicated_completions": (int,),
    "unexpected_recompiles": (int,),
}
# BENCH_kernels.json additionally pins the fused-kernel contract: the
# numeric parity of each fused kernel against its XLA reference, the
# per-arm decode throughput the headline ratio decomposes into,
# byte-identical transcripts fused-on vs fused-off, and zero unexpected
# recompiles through the batcher with both kernels live — a kernels
# bench silently dropping one of these would hide a numerics or
# retrace regression behind a valid speedup headline.
_KERNELS_REQUIRED: dict[str, tuple[type, ...]] = {
    "parity": (dict,),
    "tokens_per_s": (dict,),
    "transcripts_byte_identical": (dict,),
    "unexpected_recompiles": (int,),
}
# BENCH_capacity.json additionally pins the capacity frontier
# (tools/load_replay.py): the per-arm frontier dict (>=2 knob arms,
# each with a numeric debates/s at SLO) and the SLO it was measured
# against. A frontier whose headline drops >10% vs the committed value
# (vs_baseline < 0.9) is a capacity REGRESSION — it fails the gate
# even though the file is otherwise schema-valid.
_CAPACITY_REQUIRED: dict[str, tuple[type, ...]] = {
    "frontier": (dict,),
    "slo": (dict,),
}


def _check_fields(
    payload: dict, required: dict[str, tuple[type, ...]], label: str
) -> list[str]:
    problems = []
    for name, types in required.items():
        if name not in payload:
            problems.append(f"{label}: missing field {name!r}")
        elif not isinstance(payload[name], types) or isinstance(
            payload[name], bool
        ):
            problems.append(
                f"{label}: field {name!r} expected "
                f"{'/'.join(t.__name__ for t in types)}, got "
                f"{type(payload[name]).__name__}"
            )
    return problems


def validate_bench_file(path: Path) -> tuple[dict | None, list[str]]:
    """Validate one BENCH file; returns (trend row, problems). The row
    is None when the file is too malformed to summarize."""
    try:
        payload = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        return None, [f"{path.name}: unreadable ({e})"]
    if not isinstance(payload, dict):
        return None, [f"{path.name}: not a JSON object"]
    mode = path.stem.split("_", 1)[1] if "_" in path.stem else path.stem

    if "metric" in payload or "parsed" not in payload and "n" not in payload:
        # Metric-style: the payload IS the headline.
        problems = _check_fields(payload, _METRIC_REQUIRED, path.name)
        if mode == "serve":
            problems.extend(
                _check_fields(payload, _SERVE_REQUIRED, path.name)
            )
        if mode == "residency":
            problems.extend(
                _check_fields(payload, _RESIDENCY_REQUIRED, path.name)
            )
            ident = payload.get("transcripts_byte_identical")
            if isinstance(ident, dict) and not all(ident.values()):
                problems.append(
                    f"{path.name}: transcripts_byte_identical has a "
                    f"false arm: {ident}"
                )
        if mode == "elastic":
            problems.extend(
                _check_fields(payload, _ELASTIC_REQUIRED, path.name)
            )
            ident = payload.get("transcripts_byte_identical")
            if isinstance(ident, dict) and not all(ident.values()):
                problems.append(
                    f"{path.name}: transcripts_byte_identical has a "
                    f"false arm: {ident}"
                )
            if payload.get("duplicated_completions"):
                problems.append(
                    f"{path.name}: duplicated_completions must be 0, "
                    f"got {payload['duplicated_completions']}"
                )
        if mode == "disagg":
            problems.extend(
                _check_fields(payload, _DISAGG_REQUIRED, path.name)
            )
            ident = payload.get("transcripts_byte_identical")
            if isinstance(ident, dict) and not all(ident.values()):
                problems.append(
                    f"{path.name}: transcripts_byte_identical has a "
                    f"false arm: {ident}"
                )
            for gate in ("duplicated_completions", "unexpected_recompiles"):
                if payload.get(gate):
                    problems.append(
                        f"{path.name}: {gate} must be 0, "
                        f"got {payload[gate]}"
                    )
        if mode == "capacity":
            problems.extend(
                _check_fields(payload, _CAPACITY_REQUIRED, path.name)
            )
            frontier = payload.get("frontier")
            if isinstance(frontier, dict):
                if len(frontier) < 2:
                    problems.append(
                        f"{path.name}: frontier needs >=2 knob arms, "
                        f"got {len(frontier)}"
                    )
                for arm, entry in frontier.items():
                    dps = (
                        entry.get("debates_per_s")
                        if isinstance(entry, dict)
                        else None
                    )
                    if not isinstance(dps, (int, float)) or isinstance(
                        dps, bool
                    ):
                        problems.append(
                            f"{path.name}: frontier arm {arm!r} missing "
                            f"numeric debates_per_s"
                        )
            vs = payload.get("vs_baseline")
            if (
                isinstance(vs, (int, float))
                and not isinstance(vs, bool)
                and vs < 0.9
            ):
                problems.append(
                    f"{path.name}: capacity frontier dropped >10% vs "
                    f"the committed value (vs_baseline={vs})"
                )
        if mode == "kernels":
            problems.extend(
                _check_fields(payload, _KERNELS_REQUIRED, path.name)
            )
            for gate in ("parity", "transcripts_byte_identical"):
                vals = payload.get(gate)
                if isinstance(vals, dict) and not all(vals.values()):
                    problems.append(
                        f"{path.name}: {gate} has a false arm: {vals}"
                    )
            if payload.get("unexpected_recompiles"):
                problems.append(
                    f"{path.name}: unexpected_recompiles must be 0, "
                    f"got {payload['unexpected_recompiles']}"
                )
        if problems:
            return None, problems
        row = {
            "file": path.name,
            "mode": mode,
            "metric": payload["metric"],
            "value": payload["value"],
            "unit": payload["unit"],
            "platform": payload["platform"],
            "within_budget": payload.get("within_budget"),
            "vs_baseline": payload.get("vs_baseline"),
        }
        if mode == "serve":
            row["shed_fraction"] = payload["shed_fraction"]
            row["brownout_transitions"] = payload["brownout_transitions"]
        return row, []

    # Ladder wrapper: the headline lives in ``parsed``. Any parsed
    # payload PRESENT must schema-validate (a failed run may still
    # carry one, and its fields flow into the table); rc 0 with no
    # parsed payload is a wrapper bug.
    problems = _check_fields(payload, _LADDER_REQUIRED, path.name)
    parsed = payload.get("parsed")
    if payload.get("rc") == 0 and not isinstance(parsed, dict):
        problems.append(f"{path.name}: rc 0 but no parsed metric payload")
    if isinstance(parsed, dict):
        problems.extend(
            _check_fields(parsed, _METRIC_REQUIRED, f"{path.name}:parsed")
        )
    if problems:
        return None, problems
    row = {
        "file": path.name,
        "mode": mode,
        "metric": None,
        "value": None,
        "unit": None,
        "platform": None,
        "within_budget": None,
        "vs_baseline": None,
        "rc": payload["rc"],
    }
    if isinstance(parsed, dict):
        row.update(
            metric=parsed.get("metric"),
            value=parsed.get("value"),
            unit=parsed.get("unit"),
            platform=parsed.get("platform"),
            within_budget=parsed.get("within_budget"),
            vs_baseline=parsed.get("vs_baseline"),
        )
    return row, []


def collect(bench_dir: Path) -> tuple[list[dict], list[str]]:
    rows: list[dict] = []
    problems: list[str] = []
    for path in sorted(bench_dir.glob("BENCH_*.json")):
        row, file_problems = validate_bench_file(path)
        problems.extend(file_problems)
        if row is not None:
            rows.append(row)
    return rows, problems


def render_table(rows: list[dict]) -> str:
    if not rows:
        return "(no bench files)"
    header = ("file", "mode", "metric", "value", "unit", "platform", "ok")
    body = []
    for r in rows:
        # Defensive on optional fields: within_budget/vs_baseline are
        # not schema-required, so render survives any JSON value there.
        ok = r.get("within_budget")
        body.append(
            (
                r["file"],
                r["mode"],
                str(r["metric"] or "-"),
                (
                    f"{r['value']:g}"
                    if isinstance(r["value"], (int, float))
                    and not isinstance(r["value"], bool)
                    else "-"
                ),
                str(r["unit"] or "-")[:34],
                str(r["platform"] or "-"),
                "yes" if ok is True else ("BREACH" if ok is False else "-"),
            )
        )
    widths = [
        max(len(row[i]) for row in [header] + body)
        for i in range(len(header))
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(header, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.ljust(w) for c, w in zip(row, widths)) for row in body]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--dir",
        default=str(REPO),
        help="directory holding BENCH_*.json (default: repo root)",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable rows"
    )
    args = ap.parse_args(argv)
    bench_dir = Path(args.dir)
    if not bench_dir.is_dir():
        print(f"bench_trend: no such directory {bench_dir}", file=sys.stderr)
        return 2
    rows, problems = collect(bench_dir)
    if not rows and not problems:
        print(f"bench_trend: no BENCH_*.json in {bench_dir}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"rows": rows, "problems": problems}, indent=2))
    else:
        print(render_table(rows))
    for p in problems:
        print(f"bench_trend: {p}", file=sys.stderr)
    if problems:
        print(
            f"bench_trend: {len(problems)} schema violation(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
