"""Standalone chaos-suite runner.

Runs the fault-injection / resilience tests (pytest marker ``chaos``)
outside the main suite — the quick gate after touching scheduler, engine,
or resilience code — and optionally sweeps extra randomized fuzz seeds by
re-running the scheduler chaos fuzz under different
``ADVSPEC_CHAOS_FUZZ_SEED`` values (the in-suite fuzz pins 3 fixed seeds;
a sweep buys wider coverage when you want it, without slowing tier-1).
Reproduce a failing sweep seed N with ``ADVSPEC_CHAOS_FUZZ_SEED=N
pytest tests/test_fuzz.py -k ChaosFuzz``.

Usage:
    python tools/chaos_run.py                # pytest -m chaos
    python tools/chaos_run.py --sweep 5      # + 5 extra fuzz seeds
    python tools/chaos_run.py -- -x -k breaker   # extra pytest args
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _pytest(extra: list[str], env_overrides: dict[str, str]) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_overrides)
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "-m",
            "chaos",
            *extra,
        ],
        cwd=REPO,
        env=env,
    ).returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--sweep",
        type=int,
        default=0,
        metavar="N",
        help="after the marked suite, re-run the scheduler chaos fuzz "
        "under N extra ADVSPEC_CHAOS_FUZZ_SEED values",
    )
    args, extra = ap.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]

    rc = _pytest(extra, {})
    if rc != 0:
        return rc
    for seed in range(3, 3 + args.sweep):  # tier-1 already pins 0..2
        print(f"\n=== chaos sweep seed {seed} ===", flush=True)
        rc = _pytest(
            ["tests/test_fuzz.py", "-k", "ChaosFuzz"],
            {"ADVSPEC_CHAOS_FUZZ_SEED": str(seed)},
        )
        if rc != 0:
            return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
