"""Standalone chaos-suite runner + kill-chaos recovery drill.

Runs the fault-injection / resilience tests (pytest marker ``chaos``)
outside the main suite — the quick gate after touching scheduler, engine,
or resilience code — and optionally sweeps extra randomized fuzz seeds by
re-running the scheduler chaos fuzz under different
``ADVSPEC_CHAOS_FUZZ_SEED`` values (the in-suite fuzz pins 3 fixed seeds;
a sweep buys wider coverage when you want it, without slowing tier-1).
Reproduce a failing sweep seed N with ``ADVSPEC_CHAOS_FUZZ_SEED=N
pytest tests/test_fuzz.py -k ChaosFuzz``.

``--crash`` is the kill-chaos recovery drill (docs/resilience.md
"Durability and recovery"): it spawns a REAL mock debate round in a
subprocess, SIGKILLs it mid-round the instant the Nth opponent's
journal record becomes durable (``ADVSPEC_JOURNAL_KILL_AFTER``),
resumes the session in a second subprocess, and asserts the recovery
contract — only unfinished opponents re-issue (no duplicated opponent
work) and every journal-served transcript is byte-identical to an
uninterrupted run of the same round.

``--replica-kill`` is the FLEET variant (docs/fleet.md): a round runs
across two subprocess worker replicas sharing one content-addressed KV
store, the replica serving the round is SIGKILLed the instant its 2nd
completion crosses the pipe (``ADVSPEC_REPLICA_KILL_AFTER``), and the
drill asserts lose-a-replica-lose-nothing — the round completes on the
survivor with byte-identical transcripts vs an uninterrupted fleet
run, zero duplicated opponent attempts (per-worker serve counters +
the round journal's one-record-per-index replay), the survivor
rehydrating the shared document prefix from the disk store instead of
re-prefilling, and allocator + tier invariants clean on the survivor.

``--handoff-kill`` is the DISAGGREGATION variant (docs/fleet.md
"Disaggregation"): a 1 prefill + 1 decode worker fleet runs a debate
whose admission crosses the handoff threshold, and the prefill replica
is SIGKILLed at the worst moment — published KV blocks durable in the
shared store, decode replica not yet promoted
(``ADVSPEC_PREFILL_KILL_AFTER``). The drill asserts the decode replica
adopts the dead publisher's blocks (store rehydration, not a
re-prefill), a mid-publish kill degrades to local prefill instead of
erroring, transcripts stay byte-identical to an uninterrupted disagg
run in both variants, zero duplicated completions, the dead replica is
retired through the fleet lifecycle, and survivor invariants are
clean.

``--overload`` is the SERVE storm drill (docs/serving.md): an
in-process ``advspec serve`` daemon with tight admission caps takes an
open-loop burst several times its backlog cap and must shed, not
collapse — typed retry-after refusals, zero accepted-request loss,
interactive p99 TTFT within the drill SLO while the batch tier pauses
first (brownout), allocator/tier invariants clean.

``--weight-swap`` is the WEIGHT-RESIDENCY fault drill
(docs/weight_residency.md): two tiny real models share a 1-model HBM
budget so every round swaps, and an injected fault fires exactly at
the ``weight_swap`` seam (mid-promotion of host-demoted shards). The
drill asserts the aborted swap evicts ONLY the waiting admission (the
co-scheduled group's completions are untouched), the residency ledger
stays conservation-clean (the faulted model is still host-resident —
never lost, never double-counted), the flight-recorder JSONL autodump
reconstructs the failed swap (a ``swap_fault`` WeightEvent + the
classified FaultEvent), and the NEXT round's retry promotes the same
shards to a byte-identical transcript.

``--drain`` is the SIGTERM graceful-drain drill: a real subprocess
daemon is SIGTERMed mid-burst and must resolve every accepted debate
(finished or typed-drained), exit 0 with a clean drain report, and
leave drained sessions journal-resumable — a fresh daemon serves their
completed opponents from the journal byte-identically.

Usage:
    python tools/chaos_run.py                # pytest -m chaos
    python tools/chaos_run.py --sweep 5      # + 5 extra fuzz seeds
    python tools/chaos_run.py --crash        # SIGKILL + resume drill
    python tools/chaos_run.py --replica-kill # fleet replica-loss drill
    python tools/chaos_run.py --handoff-kill # prefill-loss handoff drill
    python tools/chaos_run.py --overload     # serve storm drill
    python tools/chaos_run.py --drain        # serve SIGTERM drain drill
    python tools/chaos_run.py --weight-swap  # weight-swap fault drill
    python tools/chaos_run.py -- -x -k breaker   # extra pytest args
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_CRASH_SPEC = (
    "## Goals\nServe heavy traffic from millions of users, fast.\n"
    "## Constraints\nThe allocator SHALL bound page reuse by refcount.\n"
)
_CRASH_MODELS = [
    "mock://critic?v=1",
    "mock://critic?v=2",
    "mock://critic?v=3",
    "mock://critic?v=4",
]
_KILL_AFTER = 2  # SIGKILL once this many completion records are durable


def _cli(args: list[str], env: dict, cwd: str, stdin: str | None = None):
    # cwd is the drill's tempdir, NOT the repo: the CLI writes
    # cwd-relative spec checkpoints, which must not litter the tree
    # (PYTHONPATH in env makes the package importable from anywhere).
    return subprocess.run(
        [sys.executable, "-m", "adversarial_spec_tpu.cli", *args],
        input=stdin,
        text=True,
        capture_output=True,
        cwd=cwd,
        env=env,
    )


def crash_drill(verbose: bool = True) -> int:
    """SIGKILL a round mid-journal, resume, and check the recovery
    contract. Returns 0 on success, 1 with reasons on stderr."""

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --crash: {msg}", flush=True)

    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="advspec-crash-") as td:
        base = {
            **os.environ,
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
        }
        # 1. The victim: a real round over 4 opponents, killed the
        # moment opponent _KILL_AFTER's completion record is durable.
        env1 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions"),
            "ADVSPEC_JOURNAL_KILL_AFTER": str(_KILL_AFTER),
        }
        p1 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env1,
            td,
            stdin=_CRASH_SPEC,
        )
        if p1.returncode != -signal.SIGKILL:
            failures.append(
                f"victim expected SIGKILL exit, got rc={p1.returncode}: "
                f"{p1.stderr[-300:]}"
            )
        say(f"victim killed mid-round (rc={p1.returncode})")

        # 2. Resume: journal-served opponents must not re-issue.
        env2 = dict(env1)
        env2.pop("ADVSPEC_JOURNAL_KILL_AFTER")
        p2 = _cli(
            ["critique", "--resume", "crash-drill", "--json"], env2, td
        )
        if p2.returncode != 0:
            failures.append(
                f"resume failed rc={p2.returncode}: {p2.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        resumed = json.loads(p2.stdout)

        # 3. Reference: the same round uninterrupted, fresh state.
        env3 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions-ref"),
        }
        p3 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env3,
            td,
            stdin=_CRASH_SPEC,
        )
        if p3.returncode != 0:
            failures.append(
                f"reference run failed rc={p3.returncode}: "
                f"{p3.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        reference = json.loads(p3.stdout)

        counters = resumed["perf"]["counters"]
        served = int(counters.get("debate/journal.served", 0))
        if served != _KILL_AFTER:
            failures.append(
                f"expected {_KILL_AFTER} journal-served opponents, "
                f"got {served}"
            )
        # No duplicated opponent work: journal-served models must have
        # burned ZERO engine attempts in the resumed process.
        for i, model in enumerate(_CRASH_MODELS):
            attempts = counters.get(f"debate/attempts.{model}", 0)
            want = 0 if i < _KILL_AFTER else 1
            if attempts != want:
                failures.append(
                    f"{model}: {attempts} engine attempt(s) on resume, "
                    f"expected {want}"
                )
        # Byte-identical transcripts for journal-served opponents (the
        # mock is deterministic, so re-issued ones match too — but the
        # journal-served equality is the recovery guarantee).
        for i in range(len(_CRASH_MODELS)):
            a = resumed["results"][i]["response"]
            b = reference["results"][i]["response"]
            if a != b:
                kind = "journal-served" if i < _KILL_AFTER else "re-issued"
                failures.append(
                    f"opponent {i} ({kind}) transcript diverged from the "
                    "uninterrupted run"
                )
        say(
            f"resume served {served} opponent(s) from the journal, "
            f"re-issued {len(_CRASH_MODELS) - served}; transcripts "
            "byte-identical"
        )
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    say("recovery contract holds")
    return 0


_FLEET_MODELS = [f"mock://critic?v={k}" for k in range(1, 5)]
_FLEET_KILL_AFTER = 2  # SIGKILL the serving replica after 2 completions
_FLEET_DEBATE_ID = "replica-drill"


def run_replica_kill(verbose: bool = True) -> tuple[list[str], dict]:
    """The fleet replica-loss drill, in-process (this process hosts the
    router; the replicas are SIGKILL-able subprocess workers). Returns
    (failures, payload) — the payload feeds ``bench.py --mode fleet``'s
    recovery phase, the failure list this CLI's verdict."""
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu.debate.core import RoundConfig, run_round
    from adversarial_spec_tpu.debate.journal import RoundJournal
    from adversarial_spec_tpu.fleet.hashring import HashRing
    from adversarial_spec_tpu.fleet.router import FleetEngine

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --replica-kill: {msg}", flush=True)

    failures: list[str] = []
    payload: dict = {
        "opponents": len(_FLEET_MODELS),
        "kill_after_completions": _FLEET_KILL_AFTER,
    }
    spec = _CRASH_SPEC * 4  # a document long enough to span store blocks
    # The ring is deterministic (sha256): compute which replica the
    # drill's debate id lands on, and arm the kill trigger for exactly
    # that replica — the survivor stays disarmed.
    primary = HashRing(["r0", "r1"]).preference(_FLEET_DEBATE_ID)[0]
    survivor = "r1" if primary == "r0" else "r0"
    payload["primary"] = primary
    payload["survivor"] = survivor

    def fleet_round(store_dir: str, sessions_dir: str, kill: bool, log_dir: str):
        worker_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "ADVSPEC_KV_TIER": "1",
            "ADVSPEC_KV_HOST_MB": "64",
            "ADVSPEC_KV_STORE_DIR": store_dir,
        }
        if kill:
            worker_env["ADVSPEC_REPLICA_KILL_AFTER"] = (
                f"{primary}:{_FLEET_KILL_AFTER}"
            )
        engine = FleetEngine(
            replicas=2,
            transport="worker",
            request_timeout_s=60.0,
            worker_env=worker_env,
            log_dir=log_dir,
        )
        fleet_mod.install_engine(engine)
        journal = RoundJournal("fleet-drill", journal_dir=Path(sessions_dir))
        cfg = RoundConfig(journal=journal, debate_id=_FLEET_DEBATE_ID)
        result = run_round(spec, _FLEET_MODELS, round_num=1, cfg=cfg)
        return engine, journal, result

    old_cfg = fleet_mod.config()
    old = (old_cfg.enabled, old_cfg.replicas, old_cfg.transport)
    fleet_mod.configure(enabled=True, replicas=2, transport="worker")
    try:
        with tempfile.TemporaryDirectory(prefix="advspec-fleet-") as td:
            # Phase A — reference: the same fleet round, uninterrupted.
            eng_a, _, ref = fleet_round(
                os.path.join(td, "store-ref"),
                os.path.join(td, "sessions-ref"),
                kill=False,
                log_dir=os.path.join(td, "logs-ref"),
            )
            fleet_mod.shutdown_fleet()
            if not all(r.ok for r in ref.responses):
                failures.append("reference fleet round had failures")
            say(f"reference round complete ({len(ref.responses)} opponents)")

            # Phase B — the kill: replica `primary` dies the instant
            # its 2nd completion line crosses the pipe, mid-round.
            fleet_mod.reset_stats()
            eng_b, journal, got = fleet_round(
                os.path.join(td, "store"),
                os.path.join(td, "sessions"),
                kill=True,
                log_dir=os.path.join(td, "logs"),
            )
            stats = fleet_mod.stats

            # 1. Zero lost debates: every opponent resolved, cleanly.
            if not all(r.ok for r in got.responses):
                failures.append(
                    "round lost work across the replica kill: "
                    + "; ".join(
                        f"{r.model}: {r.error}" for r in got.responses if not r.ok
                    )
                )
            # 2. Byte-identical transcripts vs the uninterrupted run.
            mismatched = [
                i
                for i, (a, b) in enumerate(zip(got.responses, ref.responses))
                if a.critique != b.critique
            ]
            if mismatched:
                failures.append(
                    f"transcripts diverged at opponent(s) {mismatched}"
                )
            # 3. The router's ledger: the in-flight remainder (and only
            # it) re-issued; nothing resolved twice; one replica died.
            expected_reissue = len(_FLEET_MODELS) - _FLEET_KILL_AFTER
            if stats.reissued_requests != expected_reissue:
                failures.append(
                    f"expected {expected_reissue} reissued request(s), "
                    f"got {stats.reissued_requests}"
                )
            if stats.duplicated_completions != 0:
                failures.append(
                    f"{stats.duplicated_completions} duplicated completion(s)"
                )
            if stats.replicas_retired != 1:
                failures.append(
                    f"expected 1 retired replica, got {stats.replicas_retired}"
                )
            if eng_b.router.alive_ids() != [survivor]:
                failures.append(
                    f"expected survivor {survivor}, alive: "
                    f"{eng_b.router.alive_ids()}"
                )
            # 4. No duplicated opponent ATTEMPTS: the survivor served
            # exactly the re-routed remainder, once each — never an
            # opponent the dead replica already completed.
            surv_stats = eng_b.router.replica(survivor).stats()
            expect_served = {m: 1 for m in _FLEET_MODELS[_FLEET_KILL_AFTER:]}
            if surv_stats.get("served") != expect_served:
                failures.append(
                    f"survivor served {surv_stats.get('served')}, "
                    f"expected {expect_served}"
                )
            # 5. Journal replay counters: one durable completion per
            # opponent index, each replayable exactly once.
            replayed = journal.replay(1, spec, _FLEET_MODELS)
            if sorted(replayed) != list(range(len(_FLEET_MODELS))):
                failures.append(
                    f"journal replay serves indices {sorted(replayed)}, "
                    f"expected all of 0..{len(_FLEET_MODELS) - 1}"
                )
            # 6. Store-coherent recovery: the survivor rehydrated the
            # shared document prefix from the disk store the dead
            # replica wrote through — not a cold re-prefill.
            tier = surv_stats.get("kv_tier", {})
            if not tier.get("rehydrated_blocks"):
                failures.append(
                    "survivor rehydrated nothing from the shared store "
                    f"(kv_tier: {tier})"
                )
            # 7. Clean survivors: allocator + tier invariants.
            try:
                eng_b.router.check_invariants()
            except Exception as e:
                failures.append(f"survivor invariants violated: {e}")

            payload.update(
                {
                    "reissued_requests": stats.reissued_requests,
                    "duplicated_completions": stats.duplicated_completions,
                    "survivor_served": surv_stats.get("served"),
                    "survivor_rehydrated_blocks": int(
                        tier.get("rehydrated_blocks", 0)
                    ),
                    "transcripts_byte_identical": not mismatched,
                    "recovered_fraction": round(
                        (len(_FLEET_MODELS) - stats.reissued_requests)
                        / len(_FLEET_MODELS),
                        4,
                    ),
                    "invariants_clean": not any(
                        "invariants" in f for f in failures
                    ),
                }
            )
            say(
                f"{primary} SIGKILLed after {_FLEET_KILL_AFTER} completions; "
                f"{stats.reissued_requests} request(s) re-routed to "
                f"{survivor}; transcripts "
                + ("byte-identical" if not mismatched else "DIVERGED")
            )
    finally:
        fleet_mod.shutdown_fleet()
        fleet_mod.configure(
            enabled=old[0], replicas=old[1], transport=old[2]
        )
        fleet_mod.reset_stats()
    return failures, payload


_HANDOFF_MODELS = [f"mock://critic?v={k}" for k in range(1, 5)]
_HANDOFF_DEBATE_ID = "handoff-drill"


def run_handoff_kill(verbose: bool = True) -> tuple[list[str], dict]:
    """The prefill/decode handoff-loss drill (docs/fleet.md
    "Disaggregation"): a 1 prefill + 1 decode worker fleet shares one
    content-addressed KV store, and the PREFILL replica is SIGKILLed at
    the exact worst moment — its published blocks are durable on disk
    but the decode replica has not yet promoted them
    (``ADVSPEC_PREFILL_KILL_AFTER``). The contract checked:

    1. the handoff still ADOPTS: the decode replica rehydrates the
       dead replica's shipped blocks from the store instead of
       re-prefilling (a durable publication survives its publisher);
    2. a PARTIAL publication (killed mid-publish) degrades cleanly:
       the router falls back to local prefill on the decode side, no
       error surfaces to the caller;
    3. transcripts are byte-identical to an uninterrupted disagg run
       in BOTH kill variants, with zero duplicated completions;
    4. the dead prefill replica is retired through the fleet
       lifecycle and allocator/tier invariants are clean on the
       survivor.

    Returns (failures, payload); the deterministic in-process variant
    lives in tests/test_fleet.py under the ``chaos`` marker."""
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.fleet.router import FleetEngine

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --handoff-kill: {msg}", flush=True)

    failures: list[str] = []
    spec = _CRASH_SPEC * 12  # long enough to cross the handoff threshold
    reqs = [
        ChatRequest(
            model=m,
            system="You are an adversarial spec reviewer.",
            user=f"Debate round 1\n--- DOCUMENT ---\n{spec}\n--- END ---",
            affinity_key=_HANDOFF_DEBATE_ID,
        )
        for m in _HANDOFF_MODELS
    ]
    params = SamplingParams()
    payload: dict = {
        "opponents": len(_HANDOFF_MODELS),
        "prefill_replica": "r0",
        "decode_replica": "r1",
    }

    def disagg_round(td: str, name: str, kill_after: int | None):
        """One disagg fleet round over worker replicas; r0 is the
        prefill founder, r1 the decode founder. ``kill_after`` N means
        r0 SIGKILLs itself the instant its Nth prefill result line is
        durable on the pipe (blocks already flushed to the store)."""
        worker_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "ADVSPEC_KV_TIER": "1",
            "ADVSPEC_KV_HOST_MB": "64",
            "ADVSPEC_KV_STORE_DIR": os.path.join(td, f"store-{name}"),
        }
        if kill_after is not None:
            worker_env["ADVSPEC_PREFILL_KILL_AFTER"] = f"r0:{kill_after}"
        fleet_mod.reset_stats()
        engine = FleetEngine(
            replicas=2,
            transport="worker",
            prefill_replicas=1,
            request_timeout_s=60.0,
            worker_env=worker_env,
            log_dir=os.path.join(td, f"logs-{name}"),
        )
        try:
            comps = engine.chat(reqs, params)
            snap = fleet_mod.snapshot()
            stats = fleet_mod.stats
            alive = engine.router.alive_ids()
            dec_stats = (
                engine.router.replica("r1").stats() if "r1" in alive else {}
            )
            problems: list[str] = []
            try:
                engine.router.check_invariants()
            except Exception as e:
                problems.append(str(e))
            return {
                "texts": [c.text for c in comps],
                "ok": all(c.ok for c in comps),
                "errors": [c.error for c in comps if not c.ok],
                "snap": snap,
                "duplicated": stats.duplicated_completions,
                "retired": stats.replicas_retired,
                "alive": alive,
                "rehydrated": int(
                    dec_stats.get("kv_tier", {}).get("rehydrated_blocks", 0)
                ),
                "invariant_problems": problems,
            }
        finally:
            engine.shutdown()

    with tempfile.TemporaryDirectory(prefix="advspec-handoff-") as td:
        # Phase A — reference: the same disagg round, uninterrupted.
        ref = disagg_round(td, "ref", kill_after=None)
        if not ref["ok"]:
            return [f"reference disagg round failed: {ref['errors']}"], payload
        if ref["snap"]["handoff_adopted"] != 1:
            failures.append(
                "reference round did not adopt its handoff: "
                f"{ref['snap']}"
            )
        say(
            f"reference round complete (handoff adopted, "
            f"{ref['snap']['handoff_shipped_blocks']} blocks shipped)"
        )

        # Phase B — durable-then-dead: r0 dies after ALL prefill
        # results (and their blocks) are durable, before r1 promotes.
        got = disagg_round(td, "kill", kill_after=len(_HANDOFF_MODELS))
        if not got["ok"]:
            failures.append(
                f"round lost work across the prefill kill: {got['errors']}"
            )
        if got["texts"] != ref["texts"]:
            failures.append(
                "transcripts diverged from the uninterrupted disagg run"
            )
        if got["snap"]["handoff_adopted"] != 1:
            failures.append(
                "durable publication was not adopted after the publisher "
                f"died: {got['snap']}"
            )
        if not got["rehydrated"]:
            failures.append(
                "decode replica rehydrated nothing from the dead "
                "replica's store writes"
            )
        if got["retired"] != 1:
            failures.append(
                f"expected 1 retired replica, got {got['retired']}"
            )
        if got["alive"] != ["r1"]:
            failures.append(f"expected survivor ['r1'], alive: {got['alive']}")
        if got["duplicated"]:
            failures.append(
                f"{got['duplicated']} duplicated completion(s)"
            )
        if got["invariant_problems"]:
            failures.append(
                f"survivor invariants violated: {got['invariant_problems']}"
            )
        say(
            "r0 SIGKILLed post-publication; decode adopted "
            f"{got['snap']['handoff_shipped_blocks']} durable blocks, "
            f"rehydrated {got['rehydrated']}, transcripts "
            + ("byte-identical" if got["texts"] == ref["texts"] else "DIVERGED")
        )

        # Phase C — mid-publish: r0 dies after HALF the prefill
        # results; the incomplete publication must degrade to local
        # prefill on the decode side, not error and not adopt.
        part = disagg_round(td, "partial", kill_after=2)
        if not part["ok"]:
            failures.append(
                f"partial-publish round lost work: {part['errors']}"
            )
        if part["texts"] != ref["texts"]:
            failures.append(
                "partial-publish transcripts diverged from the reference"
            )
        if part["snap"]["handoff_degraded"] != 1:
            failures.append(
                "partial publication did not degrade: "
                f"{part['snap']}"
            )
        if part["duplicated"]:
            failures.append(
                f"{part['duplicated']} duplicated completion(s) "
                "in the partial-publish variant"
            )
        if part["invariant_problems"]:
            failures.append(
                "partial-publish survivor invariants violated: "
                f"{part['invariant_problems']}"
            )
        say(
            "r0 SIGKILLed mid-publication; handoff degraded to local "
            "prefill, transcripts "
            + (
                "byte-identical"
                if part["texts"] == ref["texts"]
                else "DIVERGED"
            )
        )
        payload.update(
            {
                "shipped_blocks": got["snap"]["handoff_shipped_blocks"],
                "decode_rehydrated_blocks": got["rehydrated"],
                "adopted_after_kill": got["snap"]["handoff_adopted"] == 1,
                "degraded_on_partial": part["snap"]["handoff_degraded"] == 1,
                "transcripts_byte_identical": (
                    got["texts"] == ref["texts"]
                    and part["texts"] == ref["texts"]
                ),
                "duplicated_completions": got["duplicated"]
                + part["duplicated"],
                "invariants_clean": not (
                    got["invariant_problems"] or part["invariant_problems"]
                ),
            }
        )
    fleet_mod.reset_stats()
    return failures, payload


def handoff_kill_drill(verbose: bool = True) -> int:
    failures, _ = run_handoff_kill(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print(
            "chaos_run --handoff-kill: durable-publication adoption + "
            "partial-publish degradation hold",
            flush=True,
        )
    return 0


_OVERLOAD_SPEC = (
    "## Goals\nServe heavy traffic from millions of users, fast.\n"
    "## Constraints\n" + "The daemon SHALL shed, not collapse. " * 24
)
_OVERLOAD_MODELS = ["mock://critic?v=1", "mock://critic?v=2"]
# Interactive p99 TTFT budget for the drill (generous: the assertion is
# "bounded under overload", not "fast on a loaded CI box").
_OVERLOAD_TTFT_SLO_S = 5.0


def run_overload(verbose: bool = True) -> tuple[list[str], dict]:
    """The overload storm drill (docs/serving.md "shed, don't
    collapse"): an in-process serve daemon with TIGHT admission caps
    takes an open-loop burst several times its sustainable backlog —
    every line written before a byte is read. The contract checked:

    1. every refusal is TYPED (a SHED_REASONS member + retry_after_s);
    2. every ACCEPTED debate completes — zero lost;
    3. interactive traffic is never shed while batch still holds
       capacity, interactive p99 TTFT stays under the SLO, and the
       batch tier pauses first (brownout entered; typed ``brownout``
       sheds observed);
    4. allocator/tier invariants are clean after the storm (the
       daemon's ``check`` op);
    5. submitted == accepted + shed (nothing silently dropped).

    Returns (failures, payload) — the payload feeds ``bench.py --mode
    serve``'s overload phase, the failure list this CLI's verdict."""
    import asyncio
    import threading
    import time

    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.serve.client import ServeClient
    from adversarial_spec_tpu.serve.daemon import ServeDaemon
    from adversarial_spec_tpu.serve.driver import estimate_debate_tokens
    from adversarial_spec_tpu.serve.protocol import SHED_REASONS

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --overload: {msg}", flush=True)

    failures: list[str] = []
    n_tenants = 2
    n_interactive_per_tenant = 3  # under the depth cap: must all admit
    n_batch_per_tenant = 25  # way past every cap: must shed typed
    old = serve_mod.snapshot()
    serve_mod.reset_stats()
    serve_mod.configure(
        max_queue_depth=4,
        max_backlog_tokens=32000,
        tenant_quota_tokens=0,
        drain_deadline_s=3.0,
    )
    payload: dict = {}
    with tempfile.TemporaryDirectory(prefix="advspec-overload-") as td:
        sock = os.path.join(td, "serve.sock")
        ready = threading.Event()
        daemon = ServeDaemon(sock, sessions_dir=os.path.join(td, "sessions"))
        th = threading.Thread(
            target=lambda: asyncio.run(daemon.run(ready=ready)), daemon=True
        )
        th.start()
        if not ready.wait(10):
            return ["daemon did not come up"], {}
        client = ServeClient(sock, timeout_s=60)
        try:
            # The open-loop storm: interleave tiers, write everything,
            # read nothing until the burst is fully submitted.
            submitted: list[tuple[str, str]] = []  # (req id, tier)
            est_int = estimate_debate_tokens(
                {
                    "spec": _OVERLOAD_SPEC,
                    "models": _OVERLOAD_MODELS,
                    "max_new_tokens": 160,
                }
            )
            est_batch = estimate_debate_tokens(
                {
                    "spec": _OVERLOAD_SPEC,
                    "models": _OVERLOAD_MODELS,
                    "max_new_tokens": 1280,
                }
            )
            offered_tokens = 0
            t0 = time.monotonic()
            batch_left = {t: n_batch_per_tenant for t in range(n_tenants)}
            inter_left = {t: n_interactive_per_tenant for t in range(n_tenants)}
            while any(batch_left.values()) or any(inter_left.values()):
                for t in range(n_tenants):
                    if batch_left[t]:
                        batch_left[t] -= 1
                        offered_tokens += est_batch
                        submitted.append(
                            (
                                client.submit_debate(
                                    _OVERLOAD_SPEC,
                                    _OVERLOAD_MODELS,
                                    tenant=f"batch-{t}",
                                    tier="batch",
                                    stream=True,
                                    max_new_tokens=1280,
                                ),
                                "batch",
                            )
                        )
                    if inter_left[t]:
                        inter_left[t] -= 1
                        offered_tokens += est_int
                        submitted.append(
                            (
                                client.submit_debate(
                                    _OVERLOAD_SPEC,
                                    _OVERLOAD_MODELS,
                                    tenant=f"inter-{t}",
                                    tier="interactive",
                                    stream=True,
                                    max_new_tokens=160,
                                ),
                                "interactive",
                            )
                        )
            overload_factor = offered_tokens / serve_mod.config().max_backlog_tokens
            say(
                f"storm submitted: {len(submitted)} debates, "
                f"~{overload_factor:.1f}x the backlog cap, open-loop"
            )

            accepted = {"interactive": 0, "batch": 0}
            completed = {"interactive": 0, "batch": 0}
            shed = {"interactive": 0, "batch": 0}
            shed_reasons: dict[str, int] = {}
            lost: list[str] = []
            ttfts: list[float] = []
            for rid, tier in submitted:
                evs = client.collect(rid, timeout_s=120)
                first, last = evs[0]["event"], evs[-1]
                if first == "accepted":
                    accepted[tier] += 1
                    if last["event"] != "result":
                        lost.append(f"{rid}: terminal {last['event']}")
                        continue
                    opp_errors = [
                        r["error"]
                        for r in last.get("results", [])
                        if r["error"]
                    ]
                    if last.get("error") or opp_errors:
                        lost.append(
                            f"{rid} ({tier}): accepted but lost work: "
                            f"{last.get('error') or opp_errors[:1]}"
                        )
                    else:
                        completed[tier] += 1
                    if tier == "interactive":
                        ttfts.append(float(last["ttft_s"]))
                elif last["event"] == "shed":
                    shed[tier] += 1
                    reason = last.get("reason", "")
                    shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
                    if reason not in SHED_REASONS:
                        failures.append(f"untyped shed reason {reason!r}")
                    if not isinstance(last.get("retry_after_s"), (int, float)):
                        failures.append(f"shed without retry_after_s: {last}")
                else:
                    lost.append(f"{rid}: unexpected events {evs}")
            wall = time.monotonic() - t0

            # 1-2. zero accepted-request loss; full accounting.
            if lost:
                failures.append(
                    f"{len(lost)} accepted request(s) lost: {lost[:3]}"
                )
            total = sum(accepted.values()) + sum(shed.values())
            if total != len(submitted):
                failures.append(
                    f"accounting hole: {len(submitted)} submitted, "
                    f"{total} accounted"
                )
            # 3. tier contract: interactive fully admitted + served
            # within SLO; batch paused first (brownout + typed sheds).
            n_inter = n_tenants * n_interactive_per_tenant
            if accepted["interactive"] != n_inter:
                failures.append(
                    f"interactive shed under batch overload: "
                    f"{accepted['interactive']}/{n_inter} admitted "
                    f"(sheds: {shed_reasons})"
                )
            if shed["batch"] == 0:
                failures.append("batch tier never shed — no overload?")
            snap = serve_mod.snapshot()
            if snap["brownout_entries"] < 1:
                failures.append("brownout never entered under the storm")
            if shed_reasons.get("brownout", 0) < 1:
                failures.append("no typed brownout shed observed")
            from adversarial_spec_tpu.obs.metrics import percentile

            p99 = percentile(ttfts, 0.99)
            if p99 > _OVERLOAD_TTFT_SLO_S:
                failures.append(
                    f"interactive p99 TTFT {p99:.3f}s breaches the "
                    f"{_OVERLOAD_TTFT_SLO_S}s drill SLO"
                )
            # 4. clean invariants after the storm.
            chk = client.check()
            if not chk.get("ok"):
                failures.append(f"invariants violated: {chk.get('problems')}")
            # 5. the daemon's own ledger agrees with the client's.
            if snap["shed_fraction"] <= 0.0:
                failures.append("daemon recorded no shed under overload")
            payload = {
                "submitted": len(submitted),
                "overload_factor": round(overload_factor, 2),
                "accepted": accepted,
                "completed": completed,
                "shed": shed,
                "shed_reasons": shed_reasons,
                "shed_fraction": snap["shed_fraction"],
                "brownout_entries": snap["brownout_entries"],
                "brownout_exits": snap["brownout_exits"],
                "units_preempted": snap["units_preempted"],
                "interactive_ttft_p99_s": round(p99, 4),
                "ttft_slo_s": _OVERLOAD_TTFT_SLO_S,
                "storm_wall_s": round(wall, 3),
                "invariants_clean": bool(chk.get("ok")),
                "zero_accepted_lost": not lost,
            }
            say(
                f"{sum(accepted.values())} accepted (all served), "
                f"{sum(shed.values())} shed typed "
                f"({shed_reasons}), brownout x{snap['brownout_entries']}, "
                f"interactive p99 TTFT {p99 * 1000:.0f}ms"
            )
            client.drain()
        finally:
            client.close()
            th.join(timeout=15)
            if th.is_alive():
                failures.append("daemon failed to drain/exit")
            serve_mod.configure(
                max_queue_depth=old["max_queue_depth"],
                max_backlog_tokens=old["max_backlog_tokens"],
                tenant_quota_tokens=old["tenant_quota_tokens"],
                drain_deadline_s=old["drain_deadline_s"],
            )
    return failures, payload


def overload_drill(verbose: bool = True) -> int:
    """The full ISSUE-14 acceptance gate: the open-loop storm AND the
    SIGTERM drain drill — ``--overload`` green means both hold
    (``--drain`` runs the drain half alone)."""
    failures, _ = run_overload(verbose)
    drain_failures, _ = run_drain_drill(verbose)
    failures = failures + drain_failures
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print(
            "chaos_run --overload: shed-not-collapse + drain contract hold",
            flush=True,
        )
    return 0


_DRAIN_MODELS = [f"mock://critic?v={k}" for k in range(1, 5)]
_DRAIN_DEBATES = 48


def run_drain_drill(verbose: bool = True) -> tuple[list[str], dict]:
    """The SIGTERM graceful-drain drill (docs/serving.md "drain
    contract"): a REAL subprocess daemon takes a burst of journaled
    debates, is SIGTERMed mid-burst, and must (1) stop admissions with
    typed ``draining`` sheds, (2) resolve every accepted request —
    finished or drained with a typed error, (3) exit 0 with a parseable
    drain report, and (4) leave every drained session journal-resumable:
    a second daemon serves the completed opponents from the journal
    with zero engine work and byte-identical transcripts."""
    import time

    from adversarial_spec_tpu.serve.client import ServeClient

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --drain: {msg}", flush=True)

    failures: list[str] = []
    payload: dict = {"debates": _DRAIN_DEBATES, "opponents": len(_DRAIN_MODELS)}
    spec = _OVERLOAD_SPEC * 6

    def start_daemon(td: str, name: str, deadline_s: float):
        sock = os.path.join(td, f"{name}.sock")
        env = {
            **os.environ,
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions"),
        }
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "adversarial_spec_tpu.serve",
                "--socket",
                sock,
                "--serve-queue-depth",
                "64",
                "--serve-backlog-tokens",
                "10000000",
                "--serve-drain-deadline-s",
                str(deadline_s),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=td,
            env=env,
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"daemon died at startup: {proc.stderr.read()[-400:]}"
                )
            if os.path.exists(sock):
                try:
                    return proc, ServeClient(sock, timeout_s=60)
                except OSError:
                    pass
            time.sleep(0.02)
        proc.kill()
        raise RuntimeError("daemon socket never appeared")

    with tempfile.TemporaryDirectory(prefix="advspec-drain-") as td:
        # Phase A: burst of journaled debates, SIGTERM mid-burst.
        proc, client = start_daemon(td, "a", deadline_s=0.05)
        ids = []
        try:
            for k in range(_DRAIN_DEBATES):
                ids.append(
                    client.submit_debate(
                        spec,
                        _DRAIN_MODELS,
                        tenant=f"t{k % 3}",
                        session=f"drain-{k:02d}",
                        max_new_tokens=512,
                    )
                )
            proc.send_signal(signal.SIGTERM)
            say(f"SIGTERM sent after {len(ids)} open-loop submissions")
            outcomes = {"finished": 0, "drained": 0, "shed": 0}
            resumable: list[int] = []
            for k, rid in enumerate(ids):
                evs = client.collect(rid, timeout_s=60)
                last = evs[-1]
                if evs[0]["event"] == "shed":
                    outcomes["shed"] += 1
                    if last.get("reason") != "draining":
                        failures.append(
                            f"post-SIGTERM shed typed {last.get('reason')!r},"
                            " expected 'draining'"
                        )
                elif last["event"] != "result":
                    failures.append(
                        f"accepted debate {rid} never resolved: "
                        f"{[e['event'] for e in evs]}"
                    )
                else:
                    errors = [
                        r["error"] for r in last["results"] if r["error"]
                    ]
                    if not errors and not last.get("error"):
                        outcomes["finished"] += 1
                    else:
                        outcomes["drained"] += 1
                        resumable.append(k)
                        for e in errors:
                            if "drained" not in e and "shed" not in e:
                                failures.append(
                                    f"drained debate carries untyped "
                                    f"error {e!r}"
                                )
        except (TimeoutError, ConnectionError) as e:
            failures.append(f"phase A transport failure: {e}")
            outcomes = {"finished": 0, "drained": 0, "shed": 0}
            resumable = []
        finally:
            client.close()
        rc = proc.wait(timeout=30)
        out, _err_txt = proc.communicate(timeout=10)
        if rc != 0:
            failures.append(f"daemon exited rc={rc}, expected 0")
        report = None
        for line in reversed(out.strip().splitlines()):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                continue
            if cand.get("event") == "drain_report":
                report = cand
                break
        if report is None:
            failures.append("no drain_report on daemon stdout")
        elif not report.get("clean_exit"):
            failures.append(f"drain report not clean: {report}")
        say(
            f"daemon exited rc={rc}: {outcomes['finished']} finished, "
            f"{outcomes['drained']} drained (journal-resumable), "
            f"{outcomes['shed']} shed at admission"
        )
        if not resumable and outcomes["shed"] == 0 and not failures:
            # The box outran the drill: everything finished before the
            # deadline. Still a valid drain, but say so.
            say("note: all debates finished before the drain deadline")

        # Phase B: resume on a fresh daemon — journal-served opponents
        # must re-issue ZERO engine work and match a finished debate's
        # transcripts byte-for-byte (same spec, same round, mock
        # determinism + the journal's byte-identity guarantee).
        proc2, client2 = start_daemon(td, "b", deadline_s=5.0)
        served_total = 0
        try:
            reference = None
            ref_rid = client2.submit_debate(
                spec, _DRAIN_MODELS, tenant="ref", session="drain-ref",
                max_new_tokens=512,
            )
            ref = client2.collect(ref_rid, timeout_s=60)[-1]
            if ref["event"] == "result" and not ref.get("error"):
                reference = [r["response"] for r in ref["results"]]
            else:
                failures.append("phase B reference debate failed")
            for k in resumable:
                rid = client2.submit_debate(
                    spec,
                    _DRAIN_MODELS,
                    tenant=f"t{k % 3}",
                    session=f"drain-{k:02d}",
                    max_new_tokens=512,
                )
                last = client2.collect(rid, timeout_s=60)[-1]
                if last["event"] != "result" or last.get("error"):
                    failures.append(f"resume of drain-{k:02d} failed")
                    continue
                served_total += int(last.get("journal_served", 0))
                errors = [r["error"] for r in last["results"] if r["error"]]
                if errors:
                    failures.append(
                        f"resume of drain-{k:02d} still lossy: {errors[:1]}"
                    )
                if reference is not None:
                    got = [r["response"] for r in last["results"]]
                    if got != reference:
                        failures.append(
                            f"drain-{k:02d} resumed transcripts diverged"
                        )
            client2.drain()
        except (TimeoutError, ConnectionError, RuntimeError) as e:
            failures.append(f"phase B transport failure: {e}")
        finally:
            client2.close()
            try:
                proc2.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc2.kill()
                failures.append("phase B daemon failed to drain")
        say(
            f"resumed {len(resumable)} drained debate(s): "
            f"{served_total} opponent(s) served from journals, "
            "transcripts byte-identical"
        )
        payload.update(
            {
                "sigterm_rc": rc,
                "outcomes": outcomes,
                "drain_report_clean": bool(report and report.get("clean_exit")),
                "resumable_debates": len(resumable),
                "journal_served_on_resume": served_total,
                "zero_accepted_lost": not any(
                    "never resolved" in f for f in failures
                ),
            }
        )
    return failures, payload


def drain_drill(verbose: bool = True) -> int:
    failures, _ = run_drain_drill(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print("chaos_run --drain: drain contract holds", flush=True)
    return 0


def replica_kill_drill(verbose: bool = True) -> int:
    failures, _ = run_replica_kill(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print("chaos_run --replica-kill: recovery contract holds", flush=True)
    return 0


def run_weight_swap(verbose: bool = True) -> tuple[list[str], dict]:
    """The weight-swap fault drill (see module docstring): a fault
    mid-promotion must cost one degraded admission and one retry —
    never a lost model, a corrupted ledger, or a silent swap."""
    import jax  # noqa: F401 — force CPU backend init before the engine

    from adversarial_spec_tpu import obs
    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine import weightres
    from adversarial_spec_tpu.engine.tpu import TpuEngine
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams
    from adversarial_spec_tpu.obs.events import validate_event
    from adversarial_spec_tpu.resilience import injector

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --weight-swap: {msg}", flush=True)

    failures: list[str] = []
    payload: dict = {}
    tmp = tempfile.mkdtemp(prefix="chaos_weight_swap_")
    events_out = os.path.join(tmp, "events.jsonl")
    obs.configure(enabled=True, events_out=events_out, dump_on_fault=True)
    obs.reset_stats()
    weightres.configure(enabled=True, host_mb=4096)
    weightres.reset_stats()
    spec_mod.configure(enabled=False)
    sampling = SamplingParams(max_new_tokens=12, greedy=True, seed=0)

    def round_reqs():
        return [
            ChatRequest(
                model=f"tpu://{a}",
                system="You are an adversarial spec critic.",
                user="Critique the document.\nDebate round 1",
            )
            for a in ("random-tiny", "random-mistral-tiny")
        ]

    eng = TpuEngine()
    say("round 0: sizing the budget off the first model alone")
    probe = eng.chat(round_reqs()[:1], sampling)
    if not all(c.ok for c in probe):
        return [f"sizing round failed: {[c.error for c in probe]}"], payload
    one = max(
        e.bytes_device for e in eng.ledger._entries.values()
    )
    # Fits ONE model: loading the second must demote the first, and
    # every later round swaps through the host tier.
    os.environ["ADVSPEC_HBM_BUDGET_BYTES"] = str(int(one * 1.5))
    try:
        say("round 1 (1-model budget): forcing the demotion")
        base = eng.chat(round_reqs(), sampling)
        if not all(c.ok for c in base):
            failures.append(f"round 1 failed: {[c.error for c in base]}")
        demoted = [
            a for a in ("random-tiny", "random-mistral-tiny")
            if eng.ledger.is_host(a)
        ]
        if not demoted:
            failures.append("no model demoted under the 1-model budget")
        victim = demoted[0] if demoted else "random-tiny"
        say(f"round 2: injected fault at the {victim} promotion")
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec("device_lost@weight_swap:times=1")
            )
        )
        r2 = eng.chat(round_reqs(), sampling)
        injector.install(None)
        by_model = {
            req.model.split("//")[1]: comp
            for req, comp in zip(round_reqs(), r2)
        }
        hurt = by_model[victim]
        other = next(
            c for a, c in by_model.items() if a != victim
        )
        if hurt.ok:
            failures.append(
                "faulted promotion's admission did not degrade"
            )
        elif not hurt.transient:
            failures.append(
                f"injected swap fault classified non-transient: "
                f"{hurt.error}"
            )
        if not other.ok:
            failures.append(
                "co-scheduled group was evicted by someone else's "
                f"swap fault: {other.error}"
            )
        if not eng.ledger.is_host(victim):
            failures.append(
                f"aborted swap lost the host entry for {victim} "
                f"(state={eng.ledger.state(victim)!r})"
            )
        try:
            eng.check_residency_invariants()
        except RuntimeError as e:
            failures.append(f"residency ledger invariant violated: {e}")
        if weightres.stats.swap_faults != 1:
            failures.append(
                f"expected 1 swap fault, saw {weightres.stats.swap_faults}"
            )
        # The autodump must reconstruct the failed swap.
        dump = os.path.join(tmp, "events.fault.jsonl")
        if not os.path.exists(dump):
            failures.append("fault autodump was not written")
        else:
            lines = [
                json.loads(ln)
                for ln in Path(dump).read_text().splitlines()
                if ln
            ]
            bad = [p for ln in lines for p in validate_event(ln)]
            if bad:
                failures.append(f"autodump schema violations: {bad[:3]}")
            sf = [
                e for e in lines
                if e["type"] == "weight" and e["op"] == "swap_fault"
            ]
            if not sf:
                failures.append("autodump lacks the swap_fault event")
            elif sf[-1]["alias"] != victim:
                failures.append(
                    f"swap_fault names {sf[-1]['alias']!r}, not the "
                    f"victim {victim!r}"
                )
            if not any(e["type"] == "fault" for e in lines):
                failures.append("autodump lacks the classified fault")
            payload["autodump_events"] = len(lines)
        say("round 3: the retry must promote the same shards")
        r3 = eng.chat(round_reqs(), sampling)
        if not all(c.ok for c in r3):
            failures.append(f"retry round failed: {[c.error for c in r3]}")
        if [c.text for c in r3] != [c.text for c in base]:
            failures.append(
                "retry transcripts are not byte-identical to the "
                "pre-fault round"
            )
        try:
            eng.check_residency_invariants()
        except RuntimeError as e:
            failures.append(f"post-retry ledger invariant violated: {e}")
        payload.update(
            victim=victim,
            swap_faults=weightres.stats.swap_faults,
            promotions=weightres.stats.promotions,
            transcripts_byte_identical=(
                [c.text for c in r3] == [c.text for c in base]
            ),
        )
    finally:
        os.environ.pop("ADVSPEC_HBM_BUDGET_BYTES", None)
        injector.install(None)
    return failures, payload


_SCALE_SPEC = (
    "## Goals\nAbsorb a demand step without shedding accepted work.\n"
    "## Constraints\n" + "The fleet SHALL grow before it sheds. " * 10
)
_SCALE_MODELS = ["mock://critic?v=1", "mock://critic?v=2"]
_SCALE_SAMPLE_KEYS = 2000  # affinity keys sampled for ring-movement math


def _ring_movement(before: list[str], after: list[str]) -> float:
    """Fraction of a fixed key sample whose PRIMARY owner changes
    between two memberships, on real ``HashRing`` instances — the
    consistent-hashing contract (≈1/N keys move per membership change,
    not a full reshuffle) measured against the drill's actual replica
    ids."""
    from adversarial_spec_tpu.fleet.hashring import HashRing

    ra, rb = HashRing(before), HashRing(after)
    moved = sum(
        1
        for k in range(_SCALE_SAMPLE_KEYS)
        if ra.primary(f"debate-{k}") != rb.primary(f"debate-{k}")
    )
    return moved / _SCALE_SAMPLE_KEYS


def run_scale_storm(verbose: bool = True) -> tuple[list[str], dict]:
    """The elastic-fleet load-step drill (docs/fleet.md "grow before
    you shed"): an in-process serve daemon with a TIGHT per-replica
    backlog cap and an elastic fleet (floor 1, ceiling 3) takes an
    open-loop load step. The contract checked:

    1. scale-out ENGAGES BEFORE any shed (first ScaleEvent precedes
       the first shed ServeEvent in the flight recorder — capacity
       grows under pressure before admission refuses);
    2. zero accepted-request loss across every membership change;
    3. each membership change moves ≈1/N of the affinity keyspace
       (consistent hashing, measured on the drill's real rings);
    4. the backlog's collapse after the step drives scale-IN back to
       the floor with zero duplicated completions (the lose-nothing
       drain handoff);
    5. allocator/tier invariants are clean after the storm (the
       daemon's ``check`` op).

    Returns (failures, payload); the deterministic mock-clock variant
    lives in tests/test_autoscale.py under the ``chaos`` marker."""
    import asyncio
    import threading
    import time

    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu import obs as obs_mod
    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.serve.client import ServeClient
    from adversarial_spec_tpu.serve.daemon import ServeDaemon
    from adversarial_spec_tpu.serve.protocol import SHED_REASONS

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --scale-storm: {msg}", flush=True)

    failures: list[str] = []
    n_debates = 18
    old_serve = serve_mod.snapshot()
    old_fleet = fleet_mod.config()
    old_obs = obs_mod.config().enabled
    serve_mod.reset_stats()
    serve_mod.configure(
        max_queue_depth=64,
        max_backlog_tokens=4000,  # PER-REPLICA: elastic cap = N x this
        tenant_quota_tokens=0,
        drain_deadline_s=3.0,
    )
    fleet_mod.shutdown_fleet()
    fleet_mod.configure(
        enabled=True,
        replicas=1,  # founders start AT the floor
        transport="inproc",
        autoscale=True,
        min_replicas=1,
        max_replicas=3,
        scale_out_fraction=0.6,
        scale_in_fraction=0.15,
        scale_out_ticks=1,
        scale_in_ticks=3,
        scale_cooldown_s=0.1,
        scale_interval_s=0.01,
    )
    fleet_mod.reset_stats()
    old_ring = obs_mod.config().recorder_size
    # The ordering + membership assertions replay the WHOLE storm from
    # the flight recorder; size the ring so step-event volume cannot
    # age the early scale/shed events out.
    obs_mod.configure(enabled=True, recorder_size=131072)
    obs_mod.reset_stats()
    payload: dict = {}
    with tempfile.TemporaryDirectory(prefix="advspec-scale-") as td:
        sock = os.path.join(td, "serve.sock")
        ready = threading.Event()
        daemon = ServeDaemon(sock, sessions_dir=os.path.join(td, "sessions"))
        th = threading.Thread(
            target=lambda: asyncio.run(daemon.run(ready=ready)), daemon=True
        )
        th.start()
        if not ready.wait(10):
            return ["daemon did not come up"], {}
        client = ServeClient(sock, timeout_s=60)
        try:
            # The load step: open-loop, but PACED like a demand ramp
            # (a storm front arrives over tens of milliseconds, not in
            # one scheduler quantum) — the elasticity claim is "grows
            # under a step", not "wins a race with a synchronous
            # burst".
            t0 = time.monotonic()
            submitted = []
            for k in range(n_debates):
                submitted.append(
                    client.submit_debate(
                        _SCALE_SPEC,
                        _SCALE_MODELS,
                        tenant=f"t{k % 2}",
                        tier="batch",
                        max_new_tokens=160,
                    )
                )
                time.sleep(0.02)
            say(f"load step submitted: {n_debates} debates, open-loop")
            accepted = completed = 0
            shed_reasons: dict[str, int] = {}
            lost: list[str] = []
            for rid in submitted:
                evs = client.collect(rid, timeout_s=120)
                first, last = evs[0]["event"], evs[-1]
                if first == "accepted":
                    accepted += 1
                    opp_errors = [
                        r["error"]
                        for r in last.get("results", [])
                        if r.get("error")
                    ]
                    if (
                        last["event"] != "result"
                        or last.get("error")
                        or opp_errors
                    ):
                        lost.append(f"{rid}: {last.get('error') or last['event']}")
                    else:
                        completed += 1
                elif last["event"] == "shed":
                    reason = last.get("reason", "")
                    shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
                    if reason not in SHED_REASONS:
                        failures.append(f"untyped shed reason {reason!r}")
                else:
                    lost.append(f"{rid}: unexpected events {evs}")
            wall = time.monotonic() - t0
            # Let the post-step idle drive scale-in BEFORE replaying
            # the recorder, so the membership history below covers the
            # whole lifecycle (out AND in).
            deadline = time.monotonic() + 8.0
            while (
                fleet_mod.stats.scale_ins < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)

            # 1. the fleet grew, and grew BEFORE any shed.
            if fleet_mod.stats.scale_outs < 1:
                failures.append("load step never triggered a scale-out")
            events = obs_mod.recorder.events()
            scale_seqs = [
                e["seq"] for e in events if e["type"] == "scale"
            ]
            shed_seqs = [
                e["seq"]
                for e in events
                if e["type"] == "serve" and e["op"] == "shed"
            ]
            if shed_seqs and (
                not scale_seqs or min(scale_seqs) > min(shed_seqs)
            ):
                failures.append(
                    "admission shed before the autoscaler engaged "
                    f"(first shed seq {min(shed_seqs)}, first scale "
                    f"seq {min(scale_seqs) if scale_seqs else 'never'})"
                )
            # 2. zero accepted-request loss.
            if lost:
                failures.append(
                    f"{len(lost)} accepted request(s) lost: {lost[:3]}"
                )
            if accepted + sum(shed_reasons.values()) != n_debates:
                failures.append("accounting hole in the storm ledger")

            # 3. ≈1/N key movement per membership change, on the real
            # ring implementation with the drill's replica ids.
            memberships: list[list[str]] = [["r0"]]
            for e in events:
                if e["type"] != "scale":
                    continue
                cur = list(memberships[-1])
                if e["op"] == "serving" and e["replica"] not in cur:
                    memberships.append(sorted(cur + [e["replica"]]))
                elif e["op"] == "draining" and e["replica"] in cur:
                    cur.remove(e["replica"])
                    memberships.append(cur)
            movements = []
            for before, after in zip(memberships, memberships[1:]):
                frac = _ring_movement(before, after)
                n_ref = max(len(before), len(after))
                movements.append(round(frac, 4))
                if not (0.5 / n_ref) <= frac <= min(1.0, 2.0 / n_ref):
                    failures.append(
                        f"membership change {before}->{after} moved "
                        f"{frac:.0%} of keys (expected ~{1 / n_ref:.0%})"
                    )

            # 4. the step's collapse drives scale-in back to the
            # floor, with the lose-nothing drain handoff.
            if fleet_mod.stats.scale_ins < 1:
                failures.append("idle fleet never scaled back in")
            if fleet_mod.stats.duplicated_completions:
                failures.append(
                    f"{fleet_mod.stats.duplicated_completions} duplicated "
                    "completion(s) across membership changes"
                )
            # 5. clean invariants after the storm.
            chk = client.check()
            if not chk.get("ok"):
                failures.append(f"invariants violated: {chk.get('problems')}")
            payload = {
                "submitted": n_debates,
                "accepted": accepted,
                "completed": completed,
                "shed_reasons": shed_reasons,
                "scale_outs": fleet_mod.stats.scale_outs,
                "scale_ins": fleet_mod.stats.scale_ins,
                "spawn_failures": fleet_mod.stats.spawn_failures,
                "flaps_suppressed": fleet_mod.stats.flaps_suppressed,
                "duplicated_completions": (
                    fleet_mod.stats.duplicated_completions
                ),
                "key_movement_per_change": movements,
                "memberships": [len(m) for m in memberships],
                "storm_wall_s": round(wall, 3),
                "invariants_clean": bool(chk.get("ok")),
                "zero_accepted_lost": not lost,
            }
            say(
                f"{accepted} accepted ({completed} completed), "
                f"{sum(shed_reasons.values())} shed, "
                f"{fleet_mod.stats.scale_outs} scale-out(s), "
                f"{fleet_mod.stats.scale_ins} scale-in(s), "
                f"key movement {movements}"
            )
            client.drain()
        finally:
            client.close()
            th.join(timeout=15)
            if th.is_alive():
                failures.append("daemon failed to drain/exit")
            serve_mod.configure(
                max_queue_depth=old_serve["max_queue_depth"],
                max_backlog_tokens=old_serve["max_backlog_tokens"],
                tenant_quota_tokens=old_serve["tenant_quota_tokens"],
                drain_deadline_s=old_serve["drain_deadline_s"],
            )
            fleet_mod.shutdown_fleet()
            fleet_mod.configure(
                enabled=old_fleet.enabled,
                replicas=old_fleet.replicas,
                transport=old_fleet.transport,
                autoscale=old_fleet.autoscale,
                min_replicas=old_fleet.min_replicas,
                max_replicas=old_fleet.max_replicas,
                scale_out_fraction=old_fleet.scale_out_fraction,
                scale_in_fraction=old_fleet.scale_in_fraction,
                scale_out_ticks=old_fleet.scale_out_ticks,
                scale_in_ticks=old_fleet.scale_in_ticks,
                scale_cooldown_s=old_fleet.scale_cooldown_s,
                scale_interval_s=old_fleet.scale_interval_s,
            )
            fleet_mod.reset_stats()
            obs_mod.configure(enabled=old_obs, recorder_size=old_ring)
    return failures, payload


def scale_storm_drill(verbose: bool = True) -> int:
    failures, _ = run_scale_storm(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print(
            "chaos_run --scale-storm: warm-before-ring growth + "
            "lose-nothing scale-in + ~1/N ring movement hold",
            flush=True,
        )
    return 0


def weight_swap_drill(verbose: bool = True) -> int:
    failures, _ = run_weight_swap(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print(
            "chaos_run --weight-swap: aborted-swap containment + "
            "ledger conservation + autodump reconstruction hold",
            flush=True,
        )
    return 0


def run_deadlock_hammer(verbose: bool = True) -> tuple[list[str], dict]:
    """The lock-discipline drill (docs/locking.md), two phases:

    1. CLEAN STORM: a real ServeScheduler and a real Autoscaler over a
       real in-proc fleet, all locks tracked by the lockdep sanitizer
       (resilience/lockdep.py). Worker threads hammer exactly the
       cross-component paths that hold one lock while taking another:
       autoscaler ticks (Autoscaler._lock -> ServeScheduler._lock via
       the pressure observer, -> FleetRouter._mlock via membership
       surgery), admissions whose capacity provider reads the ring
       under the scheduler lock (ServeScheduler._lock ->
       FleetRouter._mlock), and router health rounds. Contract: the
       sanitizer records cross-lock edges (the storm really exercised
       nesting) and ZERO violations — the shipped hierarchy is acyclic
       under real concurrency, not just under GL-LOCK-ORDER's static
       graph.

    2. SEEDED INVERSION: two fresh tracked locks driven through a
       deterministic two-thread A->B / B->A inversion — the threads
       run SEQUENTIALLY (start+join each), so the opposite-direction
       edge is already in the graph when the second thread inverts it
       and no real deadlock is ever risked. Contract: exactly the
       seeded violation is detected, naming both stacks. Proves the
       drill would catch a phase-1 regression rather than silently
       passing with a dead sanitizer.

    Returns (failures, payload); the deterministic mock-clock variant
    lives in tests/test_lockdep.py under the ``chaos`` marker."""
    import threading
    import time

    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.fleet.autoscale import Autoscaler
    from adversarial_spec_tpu.fleet.router import FleetEngine
    from adversarial_spec_tpu.resilience import lockdep
    from adversarial_spec_tpu.serve.sched import ServeScheduler

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --deadlock-hammer: {msg}", flush=True)

    failures: list[str] = []
    payload: dict = {}
    old_serve = serve_mod.snapshot()
    old_fleet = fleet_mod.config()
    serve_mod.reset_stats()
    serve_mod.configure(
        max_queue_depth=256,
        max_backlog_tokens=100_000,
        tenant_quota_tokens=0,
        drain_deadline_s=3.0,
    )
    fleet_mod.shutdown_fleet()
    fleet_mod.configure(
        enabled=True,
        replicas=2,
        transport="inproc",
        autoscale=True,
        min_replicas=1,
        max_replicas=3,
        scale_out_fraction=0.5,
        scale_in_fraction=0.1,
        scale_out_ticks=1,
        scale_in_ticks=2,
        scale_cooldown_s=0.0,
        scale_interval_s=0.01,
    )
    lockdep.configure(enabled=True, raise_on_violation=False)
    lockdep.reset()
    try:
        # -- phase 1: clean ordered storm over the real stack ---------
        say("phase 1: concurrent admission/tick/health storm")
        eng = FleetEngine(replicas=2)
        sched = ServeScheduler()
        sched.set_capacity_provider(
            lambda: len(eng.router.alive_ids())
        )
        scaler = Autoscaler(
            eng,
            pressure=sched.pressure_snapshot,
            sleep=lambda s: None,
        )
        stop_t = time.monotonic() + 2.0
        errors: list[str] = []

        def admit_loop() -> None:
            i = 0
            try:
                while time.monotonic() < stop_t:
                    i += 1
                    deb = f"hammer-{threading.get_ident()}-{i}"
                    shed = sched.try_admit(
                        "tenant-a",
                        "interactive",
                        deb,
                        est_tokens=200,
                        models=["mock://critic", "mock://agree"],
                    )
                    if shed is None:
                        sched.pressure_snapshot()
                        sched.finish_debate(deb)
            except Exception as exc:  # noqa: BLE001 - drill boundary
                errors.append(f"admit_loop: {exc!r}")

        def tick_loop() -> None:
            try:
                while time.monotonic() < stop_t:
                    scaler.tick()
            except Exception as exc:  # noqa: BLE001 - drill boundary
                errors.append(f"tick_loop: {exc!r}")

        def health_loop() -> None:
            try:
                while time.monotonic() < stop_t:
                    eng.router.health_check()
                    eng.router.check_invariants()
            except Exception as exc:  # noqa: BLE001 - drill boundary
                errors.append(f"health_loop: {exc!r}")

        threads = [
            threading.Thread(target=admit_loop, name="hammer-admit-1"),
            threading.Thread(target=admit_loop, name="hammer-admit-2"),
            threading.Thread(target=tick_loop, name="hammer-tick"),
            threading.Thread(target=health_loop, name="hammer-health"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        if any(t.is_alive() for t in threads):
            failures.append("storm thread wedged (possible deadlock)")
        failures.extend(errors)
        edges = lockdep.order_edges()
        cross = {
            (a, b) for a, bs in edges.items() for b in bs if a != b
        }
        payload["edges"] = sorted(f"{a}->{b}" for a, b in cross)
        say(f"storm recorded {len(cross)} cross-lock edge(s)")
        if not cross:
            failures.append(
                "storm recorded no cross-lock edges — the drill did "
                "not exercise nested acquisition (dead hammer)"
            )
        storm_violations = lockdep.violations()
        if storm_violations:
            failures.append(
                "lock-order violation(s) in the real stack:\n"
                + "\n\n".join(str(v) for v in storm_violations)
            )
        eng.shutdown()
        sched.stop()

        # -- phase 2: seeded deterministic inversion ------------------
        say("phase 2: seeded two-thread A->B / B->A inversion")
        lockdep.reset()
        a = lockdep.TrackedLock("hammer.A", metrics=False)
        b = lockdep.TrackedLock("hammer.B", metrics=False)

        def forward() -> None:
            with a:
                with b:
                    pass

        def backward() -> None:
            with b:
                with a:
                    pass

        for fn in (forward, backward):  # sequential: no real deadlock
            t = threading.Thread(target=fn, name=f"hammer-{fn.__name__}")
            t.start()
            t.join(timeout=10.0)
        seeded = lockdep.violations()
        payload["seeded_violations"] = len(seeded)
        if len(seeded) != 1:
            failures.append(
                f"seeded inversion produced {len(seeded)} violation(s), "
                "expected exactly 1"
            )
        else:
            v = seeded[0]
            if v.edge != ("hammer.B", "hammer.A"):
                failures.append(f"seeded violation edge {v.edge}")
            msg = str(v)
            if "this acquisition" not in msg or "opposite edge" not in msg:
                failures.append(
                    f"seeded violation does not name both stacks: "
                    f"{msg[:200]!r}"
                )
    finally:
        lockdep.reset()
        lockdep.configure(
            enabled=lockdep.env_enabled(), raise_on_violation=False
        )
        serve_mod.configure(
            max_queue_depth=old_serve["max_queue_depth"],
            max_backlog_tokens=old_serve["max_backlog_tokens"],
            tenant_quota_tokens=old_serve["tenant_quota_tokens"],
            drain_deadline_s=old_serve["drain_deadline_s"],
        )
        serve_mod.reset_stats()
        fleet_mod.shutdown_fleet()
        fleet_mod.configure(
            enabled=old_fleet.enabled,
            replicas=old_fleet.replicas,
            transport=old_fleet.transport,
            autoscale=old_fleet.autoscale,
            min_replicas=old_fleet.min_replicas,
            max_replicas=old_fleet.max_replicas,
            scale_out_fraction=old_fleet.scale_out_fraction,
            scale_in_fraction=old_fleet.scale_in_fraction,
            scale_out_ticks=old_fleet.scale_out_ticks,
            scale_in_ticks=old_fleet.scale_in_ticks,
            scale_cooldown_s=old_fleet.scale_cooldown_s,
            scale_interval_s=old_fleet.scale_interval_s,
        )
        fleet_mod.reset_stats()
    return failures, payload


def deadlock_hammer_drill(verbose: bool = True) -> int:
    failures, _ = run_deadlock_hammer(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print(
            "chaos_run --deadlock-hammer: acyclic order under real "
            "concurrency + seeded inversion detected with both stacks",
            flush=True,
        )
    return 0


def _pytest(extra: list[str], env_overrides: dict[str, str]) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_overrides)
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "-m",
            "chaos",
            *extra,
        ],
        cwd=REPO,
        env=env,
    ).returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--sweep",
        type=int,
        default=0,
        metavar="N",
        help="after the marked suite, re-run the scheduler chaos fuzz "
        "under N extra ADVSPEC_CHAOS_FUZZ_SEED values",
    )
    ap.add_argument(
        "--crash",
        action="store_true",
        help="kill-chaos recovery drill: SIGKILL a real subprocess round "
        "mid-journal, resume, assert no duplicated opponent work and "
        "byte-identical journal-served transcripts",
    )
    ap.add_argument(
        "--replica-kill",
        action="store_true",
        help="fleet replica-loss drill: SIGKILL one of 2 worker replicas "
        "mid-round, assert the round completes on the survivor with "
        "byte-identical transcripts, zero duplicated opponent attempts, "
        "shared-store rehydration, and clean survivor invariants",
    )
    ap.add_argument(
        "--handoff-kill",
        action="store_true",
        help="prefill-loss handoff drill: SIGKILL the prefill replica of "
        "a 1+1 disagg worker fleet after its published KV blocks are "
        "durable but before the decode replica promotes them; assert "
        "store-rehydrated adoption, clean degradation on a partial "
        "publication, byte-identical transcripts, zero duplicated "
        "completions, and clean survivor invariants",
    )
    ap.add_argument(
        "--overload",
        action="store_true",
        help="serve overload storm drill: open-loop burst at several "
        "times the daemon's backlog cap; assert typed sheds with "
        "retry-after, zero accepted-request loss, interactive p99 TTFT "
        "within SLO with the batch tier paused first (brownout), and "
        "clean allocator/tier invariants",
    )
    ap.add_argument(
        "--weight-swap",
        action="store_true",
        help="weight-residency fault drill: inject a fault mid-promotion "
        "of host-demoted model shards; assert only the waiting admission "
        "degrades, the residency ledger stays conservation-clean, the "
        "JSONL autodump reconstructs the failed swap, and the retry "
        "promotes byte-identically",
    )
    ap.add_argument(
        "--scale-storm",
        action="store_true",
        help="elastic-fleet load-step drill: open-loop demand step "
        "against an autoscaled fleet (floor 1, ceiling 3); assert "
        "scale-out engages before any shed, zero accepted-request "
        "loss, ~1/N affinity-key movement per membership change, "
        "lose-nothing scale-in with zero duplicated completions, and "
        "clean allocator/tier invariants",
    )
    ap.add_argument(
        "--deadlock-hammer",
        action="store_true",
        help="lock-discipline drill: concurrent admission/autoscale/"
        "health storm over the real scheduler+fleet with the lockdep "
        "sanitizer armed (assert cross-lock edges recorded and zero "
        "order violations), then a seeded sequential two-thread "
        "inversion (assert exactly one violation naming both stacks)",
    )
    ap.add_argument(
        "--drain",
        action="store_true",
        help="serve SIGTERM drain drill: a real subprocess daemon is "
        "SIGTERMed mid-burst; assert typed draining sheds, every "
        "accepted debate resolved, exit 0 with a clean drain report, "
        "and drained sessions journal-resumable on a fresh daemon "
        "with byte-identical transcripts",
    )
    args, extra = ap.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]

    if args.crash:
        return crash_drill()
    if args.replica_kill:
        return replica_kill_drill()
    if args.handoff_kill:
        return handoff_kill_drill()
    if args.overload:
        return overload_drill()
    if args.scale_storm:
        return scale_storm_drill()
    if args.deadlock_hammer:
        return deadlock_hammer_drill()
    if args.drain:
        return drain_drill()
    if args.weight_swap:
        return weight_swap_drill()

    rc = _pytest(extra, {})
    if rc != 0:
        return rc
    for seed in range(3, 3 + args.sweep):  # tier-1 already pins 0..2
        print(f"\n=== chaos sweep seed {seed} ===", flush=True)
        rc = _pytest(
            ["tests/test_fuzz.py", "-k", "ChaosFuzz"],
            {"ADVSPEC_CHAOS_FUZZ_SEED": str(seed)},
        )
        if rc != 0:
            return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
