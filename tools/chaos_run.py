"""Standalone chaos-suite runner + kill-chaos recovery drill.

Runs the fault-injection / resilience tests (pytest marker ``chaos``)
outside the main suite — the quick gate after touching scheduler, engine,
or resilience code — and optionally sweeps extra randomized fuzz seeds by
re-running the scheduler chaos fuzz under different
``ADVSPEC_CHAOS_FUZZ_SEED`` values (the in-suite fuzz pins 3 fixed seeds;
a sweep buys wider coverage when you want it, without slowing tier-1).
Reproduce a failing sweep seed N with ``ADVSPEC_CHAOS_FUZZ_SEED=N
pytest tests/test_fuzz.py -k ChaosFuzz``.

``--crash`` is the kill-chaos recovery drill (docs/resilience.md
"Durability and recovery"): it spawns a REAL mock debate round in a
subprocess, SIGKILLs it mid-round the instant the Nth opponent's
journal record becomes durable (``ADVSPEC_JOURNAL_KILL_AFTER``),
resumes the session in a second subprocess, and asserts the recovery
contract — only unfinished opponents re-issue (no duplicated opponent
work) and every journal-served transcript is byte-identical to an
uninterrupted run of the same round.

Usage:
    python tools/chaos_run.py                # pytest -m chaos
    python tools/chaos_run.py --sweep 5      # + 5 extra fuzz seeds
    python tools/chaos_run.py --crash        # SIGKILL + resume drill
    python tools/chaos_run.py -- -x -k breaker   # extra pytest args
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_CRASH_SPEC = (
    "## Goals\nServe heavy traffic from millions of users, fast.\n"
    "## Constraints\nThe allocator SHALL bound page reuse by refcount.\n"
)
_CRASH_MODELS = [
    "mock://critic?v=1",
    "mock://critic?v=2",
    "mock://critic?v=3",
    "mock://critic?v=4",
]
_KILL_AFTER = 2  # SIGKILL once this many completion records are durable


def _cli(args: list[str], env: dict, cwd: str, stdin: str | None = None):
    # cwd is the drill's tempdir, NOT the repo: the CLI writes
    # cwd-relative spec checkpoints, which must not litter the tree
    # (PYTHONPATH in env makes the package importable from anywhere).
    return subprocess.run(
        [sys.executable, "-m", "adversarial_spec_tpu.cli", *args],
        input=stdin,
        text=True,
        capture_output=True,
        cwd=cwd,
        env=env,
    )


def crash_drill(verbose: bool = True) -> int:
    """SIGKILL a round mid-journal, resume, and check the recovery
    contract. Returns 0 on success, 1 with reasons on stderr."""

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --crash: {msg}", flush=True)

    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="advspec-crash-") as td:
        base = {
            **os.environ,
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
        }
        # 1. The victim: a real round over 4 opponents, killed the
        # moment opponent _KILL_AFTER's completion record is durable.
        env1 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions"),
            "ADVSPEC_JOURNAL_KILL_AFTER": str(_KILL_AFTER),
        }
        p1 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env1,
            td,
            stdin=_CRASH_SPEC,
        )
        if p1.returncode != -signal.SIGKILL:
            failures.append(
                f"victim expected SIGKILL exit, got rc={p1.returncode}: "
                f"{p1.stderr[-300:]}"
            )
        say(f"victim killed mid-round (rc={p1.returncode})")

        # 2. Resume: journal-served opponents must not re-issue.
        env2 = dict(env1)
        env2.pop("ADVSPEC_JOURNAL_KILL_AFTER")
        p2 = _cli(
            ["critique", "--resume", "crash-drill", "--json"], env2, td
        )
        if p2.returncode != 0:
            failures.append(
                f"resume failed rc={p2.returncode}: {p2.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        resumed = json.loads(p2.stdout)

        # 3. Reference: the same round uninterrupted, fresh state.
        env3 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions-ref"),
        }
        p3 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env3,
            td,
            stdin=_CRASH_SPEC,
        )
        if p3.returncode != 0:
            failures.append(
                f"reference run failed rc={p3.returncode}: "
                f"{p3.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        reference = json.loads(p3.stdout)

        counters = resumed["perf"]["counters"]
        served = int(counters.get("debate/journal.served", 0))
        if served != _KILL_AFTER:
            failures.append(
                f"expected {_KILL_AFTER} journal-served opponents, "
                f"got {served}"
            )
        # No duplicated opponent work: journal-served models must have
        # burned ZERO engine attempts in the resumed process.
        for i, model in enumerate(_CRASH_MODELS):
            attempts = counters.get(f"debate/attempts.{model}", 0)
            want = 0 if i < _KILL_AFTER else 1
            if attempts != want:
                failures.append(
                    f"{model}: {attempts} engine attempt(s) on resume, "
                    f"expected {want}"
                )
        # Byte-identical transcripts for journal-served opponents (the
        # mock is deterministic, so re-issued ones match too — but the
        # journal-served equality is the recovery guarantee).
        for i in range(len(_CRASH_MODELS)):
            a = resumed["results"][i]["response"]
            b = reference["results"][i]["response"]
            if a != b:
                kind = "journal-served" if i < _KILL_AFTER else "re-issued"
                failures.append(
                    f"opponent {i} ({kind}) transcript diverged from the "
                    "uninterrupted run"
                )
        say(
            f"resume served {served} opponent(s) from the journal, "
            f"re-issued {len(_CRASH_MODELS) - served}; transcripts "
            "byte-identical"
        )
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    say("recovery contract holds")
    return 0


def _pytest(extra: list[str], env_overrides: dict[str, str]) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_overrides)
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "-m",
            "chaos",
            *extra,
        ],
        cwd=REPO,
        env=env,
    ).returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--sweep",
        type=int,
        default=0,
        metavar="N",
        help="after the marked suite, re-run the scheduler chaos fuzz "
        "under N extra ADVSPEC_CHAOS_FUZZ_SEED values",
    )
    ap.add_argument(
        "--crash",
        action="store_true",
        help="kill-chaos recovery drill: SIGKILL a real subprocess round "
        "mid-journal, resume, assert no duplicated opponent work and "
        "byte-identical journal-served transcripts",
    )
    args, extra = ap.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]

    if args.crash:
        return crash_drill()

    rc = _pytest(extra, {})
    if rc != 0:
        return rc
    for seed in range(3, 3 + args.sweep):  # tier-1 already pins 0..2
        print(f"\n=== chaos sweep seed {seed} ===", flush=True)
        rc = _pytest(
            ["tests/test_fuzz.py", "-k", "ChaosFuzz"],
            {"ADVSPEC_CHAOS_FUZZ_SEED": str(seed)},
        )
        if rc != 0:
            return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
