"""Standalone chaos-suite runner + kill-chaos recovery drill.

Runs the fault-injection / resilience tests (pytest marker ``chaos``)
outside the main suite — the quick gate after touching scheduler, engine,
or resilience code — and optionally sweeps extra randomized fuzz seeds by
re-running the scheduler chaos fuzz under different
``ADVSPEC_CHAOS_FUZZ_SEED`` values (the in-suite fuzz pins 3 fixed seeds;
a sweep buys wider coverage when you want it, without slowing tier-1).
Reproduce a failing sweep seed N with ``ADVSPEC_CHAOS_FUZZ_SEED=N
pytest tests/test_fuzz.py -k ChaosFuzz``.

``--crash`` is the kill-chaos recovery drill (docs/resilience.md
"Durability and recovery"): it spawns a REAL mock debate round in a
subprocess, SIGKILLs it mid-round the instant the Nth opponent's
journal record becomes durable (``ADVSPEC_JOURNAL_KILL_AFTER``),
resumes the session in a second subprocess, and asserts the recovery
contract — only unfinished opponents re-issue (no duplicated opponent
work) and every journal-served transcript is byte-identical to an
uninterrupted run of the same round.

``--replica-kill`` is the FLEET variant (docs/fleet.md): a round runs
across two subprocess worker replicas sharing one content-addressed KV
store, the replica serving the round is SIGKILLed the instant its 2nd
completion crosses the pipe (``ADVSPEC_REPLICA_KILL_AFTER``), and the
drill asserts lose-a-replica-lose-nothing — the round completes on the
survivor with byte-identical transcripts vs an uninterrupted fleet
run, zero duplicated opponent attempts (per-worker serve counters +
the round journal's one-record-per-index replay), the survivor
rehydrating the shared document prefix from the disk store instead of
re-prefilling, and allocator + tier invariants clean on the survivor.

Usage:
    python tools/chaos_run.py                # pytest -m chaos
    python tools/chaos_run.py --sweep 5      # + 5 extra fuzz seeds
    python tools/chaos_run.py --crash        # SIGKILL + resume drill
    python tools/chaos_run.py --replica-kill # fleet replica-loss drill
    python tools/chaos_run.py -- -x -k breaker   # extra pytest args
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_CRASH_SPEC = (
    "## Goals\nServe heavy traffic from millions of users, fast.\n"
    "## Constraints\nThe allocator SHALL bound page reuse by refcount.\n"
)
_CRASH_MODELS = [
    "mock://critic?v=1",
    "mock://critic?v=2",
    "mock://critic?v=3",
    "mock://critic?v=4",
]
_KILL_AFTER = 2  # SIGKILL once this many completion records are durable


def _cli(args: list[str], env: dict, cwd: str, stdin: str | None = None):
    # cwd is the drill's tempdir, NOT the repo: the CLI writes
    # cwd-relative spec checkpoints, which must not litter the tree
    # (PYTHONPATH in env makes the package importable from anywhere).
    return subprocess.run(
        [sys.executable, "-m", "adversarial_spec_tpu.cli", *args],
        input=stdin,
        text=True,
        capture_output=True,
        cwd=cwd,
        env=env,
    )


def crash_drill(verbose: bool = True) -> int:
    """SIGKILL a round mid-journal, resume, and check the recovery
    contract. Returns 0 on success, 1 with reasons on stderr."""

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --crash: {msg}", flush=True)

    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="advspec-crash-") as td:
        base = {
            **os.environ,
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
        }
        # 1. The victim: a real round over 4 opponents, killed the
        # moment opponent _KILL_AFTER's completion record is durable.
        env1 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions"),
            "ADVSPEC_JOURNAL_KILL_AFTER": str(_KILL_AFTER),
        }
        p1 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env1,
            td,
            stdin=_CRASH_SPEC,
        )
        if p1.returncode != -signal.SIGKILL:
            failures.append(
                f"victim expected SIGKILL exit, got rc={p1.returncode}: "
                f"{p1.stderr[-300:]}"
            )
        say(f"victim killed mid-round (rc={p1.returncode})")

        # 2. Resume: journal-served opponents must not re-issue.
        env2 = dict(env1)
        env2.pop("ADVSPEC_JOURNAL_KILL_AFTER")
        p2 = _cli(
            ["critique", "--resume", "crash-drill", "--json"], env2, td
        )
        if p2.returncode != 0:
            failures.append(
                f"resume failed rc={p2.returncode}: {p2.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        resumed = json.loads(p2.stdout)

        # 3. Reference: the same round uninterrupted, fresh state.
        env3 = {
            **base,
            "ADVSPEC_SESSIONS_DIR": os.path.join(td, "sessions-ref"),
        }
        p3 = _cli(
            [
                "critique",
                "--session",
                "crash-drill",
                "--models",
                ",".join(_CRASH_MODELS),
                "--json",
            ],
            env3,
            td,
            stdin=_CRASH_SPEC,
        )
        if p3.returncode != 0:
            failures.append(
                f"reference run failed rc={p3.returncode}: "
                f"{p3.stderr[-300:]}"
            )
            print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
            return 1
        reference = json.loads(p3.stdout)

        counters = resumed["perf"]["counters"]
        served = int(counters.get("debate/journal.served", 0))
        if served != _KILL_AFTER:
            failures.append(
                f"expected {_KILL_AFTER} journal-served opponents, "
                f"got {served}"
            )
        # No duplicated opponent work: journal-served models must have
        # burned ZERO engine attempts in the resumed process.
        for i, model in enumerate(_CRASH_MODELS):
            attempts = counters.get(f"debate/attempts.{model}", 0)
            want = 0 if i < _KILL_AFTER else 1
            if attempts != want:
                failures.append(
                    f"{model}: {attempts} engine attempt(s) on resume, "
                    f"expected {want}"
                )
        # Byte-identical transcripts for journal-served opponents (the
        # mock is deterministic, so re-issued ones match too — but the
        # journal-served equality is the recovery guarantee).
        for i in range(len(_CRASH_MODELS)):
            a = resumed["results"][i]["response"]
            b = reference["results"][i]["response"]
            if a != b:
                kind = "journal-served" if i < _KILL_AFTER else "re-issued"
                failures.append(
                    f"opponent {i} ({kind}) transcript diverged from the "
                    "uninterrupted run"
                )
        say(
            f"resume served {served} opponent(s) from the journal, "
            f"re-issued {len(_CRASH_MODELS) - served}; transcripts "
            "byte-identical"
        )
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    say("recovery contract holds")
    return 0


_FLEET_MODELS = [f"mock://critic?v={k}" for k in range(1, 5)]
_FLEET_KILL_AFTER = 2  # SIGKILL the serving replica after 2 completions
_FLEET_DEBATE_ID = "replica-drill"


def run_replica_kill(verbose: bool = True) -> tuple[list[str], dict]:
    """The fleet replica-loss drill, in-process (this process hosts the
    router; the replicas are SIGKILL-able subprocess workers). Returns
    (failures, payload) — the payload feeds ``bench.py --mode fleet``'s
    recovery phase, the failure list this CLI's verdict."""
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu.debate.core import RoundConfig, run_round
    from adversarial_spec_tpu.debate.journal import RoundJournal
    from adversarial_spec_tpu.fleet.hashring import HashRing
    from adversarial_spec_tpu.fleet.router import FleetEngine

    def say(msg: str) -> None:
        if verbose:
            print(f"chaos_run --replica-kill: {msg}", flush=True)

    failures: list[str] = []
    payload: dict = {
        "opponents": len(_FLEET_MODELS),
        "kill_after_completions": _FLEET_KILL_AFTER,
    }
    spec = _CRASH_SPEC * 4  # a document long enough to span store blocks
    # The ring is deterministic (sha256): compute which replica the
    # drill's debate id lands on, and arm the kill trigger for exactly
    # that replica — the survivor stays disarmed.
    primary = HashRing(["r0", "r1"]).preference(_FLEET_DEBATE_ID)[0]
    survivor = "r1" if primary == "r0" else "r0"
    payload["primary"] = primary
    payload["survivor"] = survivor

    def fleet_round(store_dir: str, sessions_dir: str, kill: bool, log_dir: str):
        worker_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "ADVSPEC_KV_TIER": "1",
            "ADVSPEC_KV_HOST_MB": "64",
            "ADVSPEC_KV_STORE_DIR": store_dir,
        }
        if kill:
            worker_env["ADVSPEC_REPLICA_KILL_AFTER"] = (
                f"{primary}:{_FLEET_KILL_AFTER}"
            )
        engine = FleetEngine(
            replicas=2,
            transport="worker",
            request_timeout_s=60.0,
            worker_env=worker_env,
            log_dir=log_dir,
        )
        fleet_mod.install_engine(engine)
        journal = RoundJournal("fleet-drill", journal_dir=Path(sessions_dir))
        cfg = RoundConfig(journal=journal, debate_id=_FLEET_DEBATE_ID)
        result = run_round(spec, _FLEET_MODELS, round_num=1, cfg=cfg)
        return engine, journal, result

    old_cfg = fleet_mod.config()
    old = (old_cfg.enabled, old_cfg.replicas, old_cfg.transport)
    fleet_mod.configure(enabled=True, replicas=2, transport="worker")
    try:
        with tempfile.TemporaryDirectory(prefix="advspec-fleet-") as td:
            # Phase A — reference: the same fleet round, uninterrupted.
            eng_a, _, ref = fleet_round(
                os.path.join(td, "store-ref"),
                os.path.join(td, "sessions-ref"),
                kill=False,
                log_dir=os.path.join(td, "logs-ref"),
            )
            fleet_mod.shutdown_fleet()
            if not all(r.ok for r in ref.responses):
                failures.append("reference fleet round had failures")
            say(f"reference round complete ({len(ref.responses)} opponents)")

            # Phase B — the kill: replica `primary` dies the instant
            # its 2nd completion line crosses the pipe, mid-round.
            fleet_mod.reset_stats()
            eng_b, journal, got = fleet_round(
                os.path.join(td, "store"),
                os.path.join(td, "sessions"),
                kill=True,
                log_dir=os.path.join(td, "logs"),
            )
            stats = fleet_mod.stats

            # 1. Zero lost debates: every opponent resolved, cleanly.
            if not all(r.ok for r in got.responses):
                failures.append(
                    "round lost work across the replica kill: "
                    + "; ".join(
                        f"{r.model}: {r.error}" for r in got.responses if not r.ok
                    )
                )
            # 2. Byte-identical transcripts vs the uninterrupted run.
            mismatched = [
                i
                for i, (a, b) in enumerate(zip(got.responses, ref.responses))
                if a.critique != b.critique
            ]
            if mismatched:
                failures.append(
                    f"transcripts diverged at opponent(s) {mismatched}"
                )
            # 3. The router's ledger: the in-flight remainder (and only
            # it) re-issued; nothing resolved twice; one replica died.
            expected_reissue = len(_FLEET_MODELS) - _FLEET_KILL_AFTER
            if stats.reissued_requests != expected_reissue:
                failures.append(
                    f"expected {expected_reissue} reissued request(s), "
                    f"got {stats.reissued_requests}"
                )
            if stats.duplicated_completions != 0:
                failures.append(
                    f"{stats.duplicated_completions} duplicated completion(s)"
                )
            if stats.replicas_retired != 1:
                failures.append(
                    f"expected 1 retired replica, got {stats.replicas_retired}"
                )
            if eng_b.router.alive_ids() != [survivor]:
                failures.append(
                    f"expected survivor {survivor}, alive: "
                    f"{eng_b.router.alive_ids()}"
                )
            # 4. No duplicated opponent ATTEMPTS: the survivor served
            # exactly the re-routed remainder, once each — never an
            # opponent the dead replica already completed.
            surv_stats = eng_b.router.replica(survivor).stats()
            expect_served = {m: 1 for m in _FLEET_MODELS[_FLEET_KILL_AFTER:]}
            if surv_stats.get("served") != expect_served:
                failures.append(
                    f"survivor served {surv_stats.get('served')}, "
                    f"expected {expect_served}"
                )
            # 5. Journal replay counters: one durable completion per
            # opponent index, each replayable exactly once.
            replayed = journal.replay(1, spec, _FLEET_MODELS)
            if sorted(replayed) != list(range(len(_FLEET_MODELS))):
                failures.append(
                    f"journal replay serves indices {sorted(replayed)}, "
                    f"expected all of 0..{len(_FLEET_MODELS) - 1}"
                )
            # 6. Store-coherent recovery: the survivor rehydrated the
            # shared document prefix from the disk store the dead
            # replica wrote through — not a cold re-prefill.
            tier = surv_stats.get("kv_tier", {})
            if not tier.get("rehydrated_blocks"):
                failures.append(
                    "survivor rehydrated nothing from the shared store "
                    f"(kv_tier: {tier})"
                )
            # 7. Clean survivors: allocator + tier invariants.
            try:
                eng_b.router.check_invariants()
            except Exception as e:
                failures.append(f"survivor invariants violated: {e}")

            payload.update(
                {
                    "reissued_requests": stats.reissued_requests,
                    "duplicated_completions": stats.duplicated_completions,
                    "survivor_served": surv_stats.get("served"),
                    "survivor_rehydrated_blocks": int(
                        tier.get("rehydrated_blocks", 0)
                    ),
                    "transcripts_byte_identical": not mismatched,
                    "recovered_fraction": round(
                        (len(_FLEET_MODELS) - stats.reissued_requests)
                        / len(_FLEET_MODELS),
                        4,
                    ),
                    "invariants_clean": not any(
                        "invariants" in f for f in failures
                    ),
                }
            )
            say(
                f"{primary} SIGKILLed after {_FLEET_KILL_AFTER} completions; "
                f"{stats.reissued_requests} request(s) re-routed to "
                f"{survivor}; transcripts "
                + ("byte-identical" if not mismatched else "DIVERGED")
            )
    finally:
        fleet_mod.shutdown_fleet()
        fleet_mod.configure(
            enabled=old[0], replicas=old[1], transport=old[2]
        )
        fleet_mod.reset_stats()
    return failures, payload


def replica_kill_drill(verbose: bool = True) -> int:
    failures, _ = run_replica_kill(verbose)
    if failures:
        print("\n".join(f"FAIL: {f}" for f in failures), file=sys.stderr)
        return 1
    if verbose:
        print("chaos_run --replica-kill: recovery contract holds", flush=True)
    return 0


def _pytest(extra: list[str], env_overrides: dict[str, str]) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_overrides)
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-q",
            "-p",
            "no:cacheprovider",
            "-m",
            "chaos",
            *extra,
        ],
        cwd=REPO,
        env=env,
    ).returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--sweep",
        type=int,
        default=0,
        metavar="N",
        help="after the marked suite, re-run the scheduler chaos fuzz "
        "under N extra ADVSPEC_CHAOS_FUZZ_SEED values",
    )
    ap.add_argument(
        "--crash",
        action="store_true",
        help="kill-chaos recovery drill: SIGKILL a real subprocess round "
        "mid-journal, resume, assert no duplicated opponent work and "
        "byte-identical journal-served transcripts",
    )
    ap.add_argument(
        "--replica-kill",
        action="store_true",
        help="fleet replica-loss drill: SIGKILL one of 2 worker replicas "
        "mid-round, assert the round completes on the survivor with "
        "byte-identical transcripts, zero duplicated opponent attempts, "
        "shared-store rehydration, and clean survivor invariants",
    )
    args, extra = ap.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]

    if args.crash:
        return crash_drill()
    if args.replica_kill:
        return replica_kill_drill()

    rc = _pytest(extra, {})
    if rc != 0:
        return rc
    for seed in range(3, 3 + args.sweep):  # tier-1 already pins 0..2
        print(f"\n=== chaos sweep seed {seed} ===", flush=True)
        rc = _pytest(
            ["tests/test_fuzz.py", "-k", "ChaosFuzz"],
            {"ADVSPEC_CHAOS_FUZZ_SEED": str(seed)},
        )
        if rc != 0:
            return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
