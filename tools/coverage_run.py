"""Stdlib line-coverage gate (PEP 669 ``sys.monitoring``).

This container has no egress: pytest-cov/coverage.py are not
installable, so for two rounds the CI coverage gate was claimed but
never executed anywhere (CHANGELOG 0.2.0). This tool closes that gap
with zero dependencies: the same gate line runs locally and in CI.

Measurement basis matches coverage.py's: the denominator is the set of
line numbers the compiled bytecode can attribute code to (``co_lines``
over every code object, recursively), the numerator is the lines the
interpreter actually ran (``sys.monitoring`` LINE events, interpreter-
wide, all threads). Lines marked ``# pragma: no cover`` are excluded;
when the pragma sits on a ``def``/``class``/``if`` header the whole
block is excluded (ast body span).

Usage:
    python tools/coverage_run.py --fail-under 90 [pytest args...]
    # default pytest args: tests/ -q
"""

from __future__ import annotations

import argparse
import ast
import os
import sys


def executable_lines(path: str) -> set[int]:
    """Line numbers the compiled module can execute (co_lines basis)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        code = compile(source, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
        for _, _, line in co.co_lines():
            if line is not None and line > 0:
                lines.add(line)
    return lines - excluded_lines(source, path)


def excluded_lines(source: str, path: str) -> set[int]:
    """Lines under a ``# pragma: no cover`` marker.

    A pragma on a block header (any ast node with a body) excludes the
    node's whole span; elsewhere it excludes just its own line.
    """
    pragma_lines = {
        i
        for i, text in enumerate(source.splitlines(), start=1)
        if "pragma: no cover" in text
    }
    if not pragma_lines:
        return set()
    excluded = set(pragma_lines)
    try:
        tree = ast.parse(source, path)
    except SyntaxError:
        return excluded
    for node in ast.walk(tree):
        lineno = getattr(node, "lineno", None)
        end = getattr(node, "end_lineno", None)
        if (
            lineno in pragma_lines
            and end is not None
            and hasattr(node, "body")
        ):
            excluded.update(range(lineno, end + 1))
    return excluded


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--fail-under", type=float, default=0.0)
    parser.add_argument("--package", default="adversarial_spec_tpu")
    parser.add_argument("--report-all", action="store_true",
                        help="per-file table for every file, not worst-20")
    parser.add_argument("--missing", metavar="SUBSTR",
                        help="print uncovered line ranges for files whose "
                             "path contains SUBSTR")
    args, pytest_args = parser.parse_known_args()
    # Unrecognized args (and anything after --) pass through to pytest.

    if not hasattr(sys, "monitoring"):  # pragma: no cover
        print(
            "coverage_run.py needs Python >= 3.12 (sys.monitoring); "
            "run plain pytest on older interpreters",
            file=sys.stderr,
        )
        return 2
    args.pytest_args = pytest_args

    package_root = os.path.abspath(args.package)
    if not os.path.isdir(package_root):
        print(f"no such package dir: {package_root}", file=sys.stderr)
        return 2

    executed: dict[str, set[int]] = {}
    mon = sys.monitoring
    prefix = package_root + os.sep

    def on_line(code, line):
        fn = code.co_filename
        if fn.startswith(prefix):
            executed.setdefault(fn, set()).add(line)
        # Only set membership is needed: disable this (code, line)
        # location after its first hit (what coverage.py's sysmon core
        # does) so hot loops don't pay a Python callback per iteration.
        return mon.DISABLE

    mon.use_tool_id(mon.COVERAGE_ID, "advspec-cov")
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, on_line)
    mon.set_events(mon.COVERAGE_ID, mon.events.LINE)
    try:
        import pytest

        rc = pytest.main(args.pytest_args or ["tests/", "-q"])
    finally:
        mon.set_events(mon.COVERAGE_ID, 0)
        mon.free_tool_id(mon.COVERAGE_ID)
    if rc != 0:
        print(f"pytest failed (rc={rc}); coverage not evaluated",
              file=sys.stderr)
        return int(rc)

    rows = []
    total_exec = total_hit = 0
    for dirpath, _dirnames, filenames in os.walk(package_root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            lines = executable_lines(path)
            hit = executed.get(path, set()) & lines
            total_exec += len(lines)
            total_hit += len(hit)
            pct = 100.0 * len(hit) / len(lines) if lines else 100.0
            rel = os.path.relpath(path, os.path.dirname(package_root))
            rows.append((pct, rel, len(hit), len(lines)))
            if args.missing and args.missing in path:
                miss = sorted(lines - hit)
                ranges, i = [], 0
                while i < len(miss):
                    j = i
                    while j + 1 < len(miss) and miss[j + 1] == miss[j] + 1:
                        j += 1
                    ranges.append(
                        str(miss[i]) if i == j else f"{miss[i]}-{miss[j]}"
                    )
                    i = j + 1
                print(f"MISSING {rel}: {', '.join(ranges) or 'none'}")

    rows.sort()
    shown = rows if args.report_all else rows[:20]
    width = max(len(r[1]) for r in shown) if shown else 10
    for pct, rel, hit, n in shown:
        print(f"{rel:<{width}}  {hit:>5}/{n:<5}  {pct:6.1f}%")
    if not args.report_all and len(rows) > 20:
        print(f"... ({len(rows) - 20} better-covered files elided; "
              "--report-all for the full table)")
    total_pct = 100.0 * total_hit / total_exec if total_exec else 100.0
    print(f"TOTAL  {total_hit}/{total_exec}  {total_pct:.2f}%")

    if total_pct < args.fail_under:
        print(f"FAIL: coverage {total_pct:.2f}% < {args.fail_under}%",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
