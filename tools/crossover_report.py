"""Turn a tpu_ladder results file into the MIN_T decision table.

Reads the JSONL `tpu_session.sh` harvests (tpu_results/*.jsonl), prints
the kernel-vs-XLA decode table per context length, and recommends the
`ADVSPEC_PALLAS_MIN_T` default: 0 if the kernel wins everywhere, else
the smallest measured T where the kernel starts winning (a kernel-off
sentinel if it never does). Also summarizes the lever deltas
(spec/int8/paged/chunk/unroll/gamma) against the north-star baseline —
`recommended_env` turns the sweeps into ADVSPEC_* overrides that
bench.py applies automatically — so the whole tuning story reads off
one screen after a tunnel window.

Usage: python tools/crossover_report.py [tpu_results/r04.jsonl]
"""

from __future__ import annotations

import json
import sys


def load(path: str, include_smoke: bool = False) -> dict[str, dict]:
    """Parse a ladder JSONL into {step: row}. Smoke rows (CPU-tiny dry
    runs of the ladder code, tpu_ladder.py) are excluded by default —
    they must never feed tuning recommendations."""
    steps: dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "step" in d and (include_smoke or not d.get("smoke")):
                steps[d["step"]] = d  # last write wins (resumes)
    return steps


_NEVER = 1 << 31  # MIN_T sentinel: kernel off at any realistic context


def _crossover_ts(steps: dict[str, dict]) -> list[int]:
    """Context lengths with a kernel-side crossover measurement."""
    return sorted(
        int(k.split("_T")[1].split("_")[0])
        for k in steps
        if k.startswith("crossover_T") and k.endswith("_kernel")
    )


def recommended_min_t(steps: dict[str, dict]) -> int | None:
    """ADVSPEC_PALLAS_MIN_T from crossover data: 0 if the kernel wins at
    every measured T, the smallest T of a clean winning suffix
    otherwise, the _NEVER sentinel (kernel off everywhere) if it never
    wins. None when no complete pair was measured."""
    ts = _crossover_ts(steps)
    first_win = None
    measured_any = False
    for t in ts:
        k = steps.get(f"crossover_T{t}_kernel", {}).get("decode_tok_s")
        x = steps.get(f"crossover_T{t}_xla", {}).get("decode_tok_s")
        if k is None or x is None:
            continue
        measured_any = True
        if k >= x:
            if first_win is None:
                first_win = t
        else:
            first_win = None  # a loss resets: need a clean suffix
    if not measured_any:
        return None
    if first_win == ts[0]:
        return 0
    if first_win is None:
        return _NEVER  # losing at every measured T: keep the kernel off
    return first_win


def recommended_env(steps: dict[str, dict]) -> dict[str, str]:
    """Env overrides justified by harvested data (empty if none).

    The north-star step ran with chunk=128 / unroll=4 (the defaults);
    the sweep steps vary one knob each. A knob is only overridden when
    its best sweep value beats the default's measurement."""
    env: dict[str, str] = {}
    min_t = recommended_min_t(steps)
    if min_t is not None:
        env["ADVSPEC_PALLAS_MIN_T"] = str(min_t)
    base = steps.get("north_star", {}).get("decode_tok_s")
    if base:
        for knob, default, options in (
            ("ADVSPEC_DECODE_CHUNK", "128",
             {"chunk64": "64", "chunk256": "256"}),
            ("ADVSPEC_DECODE_UNROLL", "4",
             {"unroll1": "1", "unroll2": "2"}),
            ("ADVSPEC_GAMMA", "8",
             {"gamma4": "4", "gamma16": "16"}),
            # Default "0" = auto (VMEM-budget largest-fit pick).
            ("ADVSPEC_BLOCK_T", "0",
             {"blockt128": "128", "blockt256": "256"}),
        ):
            best_val, best_tok = default, base
            for step_name, val in options.items():
                tok = steps.get(step_name, {}).get("decode_tok_s")
                if tok and tok > best_tok:
                    best_val, best_tok = val, tok
            if best_val != default:
                env[knob] = best_val
        # Speculation on/off compares the two PINNED steps (spec_on
        # passes speculative=True, spec_off False — tpu_ladder.py), not
        # north_star: north_star's speculation default is itself
        # governed by ADVSPEC_SPECULATIVE, so using it as the baseline
        # would make the recommendation flap across harvest cycles.
        spec_off = steps.get("spec_off", {}).get("decode_tok_s")
        spec_on = steps.get("spec_on", {}).get("decode_tok_s")
        if spec_off and spec_on and spec_off > spec_on:
            env["ADVSPEC_SPECULATIVE"] = "0"
    return env


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "tpu_results/r04.jsonl"
    try:
        steps = load(path)
    except FileNotFoundError:
        print(f"no results file at {path}", file=sys.stderr)
        return 2

    ts = _crossover_ts(steps)
    if ts:
        print("T (ctx)   kernel tok/s   xla tok/s   winner")
        for t in ts:
            k = steps.get(f"crossover_T{t}_kernel", {}).get("decode_tok_s")
            x = steps.get(f"crossover_T{t}_xla", {}).get("decode_tok_s")
            if k is None or x is None:
                print(f"{t:<9} (incomplete)")
                continue
            print(f"{t:<9} {k:<14} {x:<11} "
                  f"{'kernel' if k >= x else 'xla'}")
        min_t = recommended_min_t(steps)
        if min_t == 0:
            print("→ ADVSPEC_PALLAS_MIN_T=0 (kernel wins everywhere)")
        elif min_t == _NEVER:
            print("→ kernel never cleanly wins: ADVSPEC_PALLAS_MIN_T="
                  f"{min_t} (kernel off) — investigate the grid")
        elif min_t is not None:
            print(f"→ ADVSPEC_PALLAS_MIN_T={min_t} (crossover; xla "
                  "below it)")
        env = recommended_env(steps)
        if env:
            print("→ tuned env: " +
                  " ".join(f"{k}={v}" for k, v in sorted(env.items())))
    else:
        print("no crossover data yet")

    base = steps.get("north_star", {}).get("decode_tok_s")
    if base:
        print(f"\nnorth_star: {base} tok/s "
              f"(cold first-call {steps['north_star'].get('cold_wall_s')}s)")
        # Derived from the harvest itself so a new ladder step can never
        # be invisible here: every decode-rate row except the baseline,
        # the crossover pairs, and the separately-printed specials.
        lever_names = sorted(
            k
            for k, v in steps.items()
            if isinstance(v.get("decode_tok_s"), (int, float))
            and k != "north_star"
            and not k.startswith("crossover_T")
            and not k.startswith("config2")
            # Tier rows print in their own table below (their workload
            # shape differs; a %-vs-north_star figure would mislead).
            and not k.startswith("tier_")
            and k != "profile_trace"
        )
        for name in lever_names:
            v = steps[name]["decode_tok_s"]
            print(f"  {name:<9} {v:>8} tok/s  ({v / base - 1:+.1%} "
                  "vs north_star)")
    # Phase B': the batcher γ sweep (per-slot speculation on the paged
    # serving path). decode_tok_s rows already print in the lever table
    # above; this adds the speculation-specific columns the crossover
    # is actually judged by.
    batcher_rows = sorted(
        k for k in steps
        if k.startswith("batcher_") and "tokens_per_step" in steps[k]
        # spec_off is the baseline line above, not a sweep point — its
        # record also carries tokens_per_step (0.0, zero spec steps)
        # and would print a contradictory duplicate row.
        and k != "batcher_spec_off"
    )
    if batcher_rows:
        off = steps.get("batcher_spec_off", {}).get("decode_tok_s")
        print("\nbatcher γ sweep    tok/s     tokens/step  acceptance")
        if off:
            print(f"  batcher_spec_off {off:<9} 1.0          -")
        for name in batcher_rows:
            row = steps[name]
            print(
                f"  {name:<16} {row.get('decode_tok_s', '?'):<9} "
                f"{row.get('tokens_per_step', '?'):<12} "
                f"{row.get('acceptance_rate', '?')}"
            )
        if off:
            best = max(
                batcher_rows,
                key=lambda n: steps[n].get("decode_tok_s") or 0,
            )
            best_tok = steps[best].get("decode_tok_s") or 0
            if best_tok <= off:
                print("  → speculation not winning in the batcher at "
                      "this workload: consider ADVSPEC_SPECULATIVE=0")
            else:
                print(f"  → best: {best} ({best_tok / off - 1:+.1%} vs "
                      "spec-off)")
    # Phase C: tiered KV — restart rehydration and the host-tier hit
    # ratio vs pool size (the pressure story engine/kvtier.py exists
    # for). These rows have no decode_tok_s baseline comparison; the
    # judgment is prefill avoided.
    tier_rows = sorted(
        (k for k in steps if k.startswith("tier_pool")),
        key=lambda k: steps[k].get("pool_tokens", 0),
    )
    if tier_rows:
        print("\ntier sweep        pool tok  host hit  promoted tok  tok/s")
        for name in tier_rows:
            row = steps[name]
            print(
                f"  {name:<15} {row.get('pool_tokens', '?'):<9} "
                f"{row.get('host_hit_ratio', '?'):<9} "
                f"{row.get('promoted_tokens', '?'):<13} "
                f"{row.get('decode_tok_s', '?')}"
            )
        hot = [
            n for n in tier_rows if (steps[n].get("host_hit_ratio") or 0) > 0
        ]
        if hot:
            print(
                "  → host tier absorbing re-prefill up to pool "
                f"{max(steps[n].get('pool_tokens', 0) for n in hot)} tok"
            )
    tr_row = steps.get("tier_restart")
    if tr_row:
        print(
            "tier_restart: "
            f"{tr_row.get('rehydrated_fraction', '?')} of restart prefill "
            f"rehydrated from the store "
            f"({tr_row.get('rehydrated_tokens', '?')} tok; "
            f"cold {tr_row.get('wall_cold_s', '?')}s → warm "
            f"{tr_row.get('wall_warm_s', '?')}s)"
        )
    lc = steps.get("long_context_16k", {}).get("prefill_tok_s")
    if lc:
        print(f"long_context_16k prefill: {lc} tok/s")
    c2 = steps.get("config2_8b_int8_greedy", {}).get("decode_tok_s")
    if c2:
        print(f"config2 (8B int8 greedy, 1 opponent): {c2} tok/s")
    tr = steps.get("profile_trace", {}).get("trace_dir")
    if tr:
        print(f"profile trace: {tr}")
    if "ladder_complete" in steps:
        print("\nladder: COMPLETE")
    else:
        missing = not ts or base is None
        print("\nladder: partial" + (" (core steps missing)" if missing
                                     else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
