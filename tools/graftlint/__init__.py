"""graftlint v2 — rule-registry static analysis over an interprocedural
dataflow engine, for JAX serving-path discipline (pure stdlib ``ast``;
mypy/ruff are not installable here).

The framework generalizes ``tools/astlint.py`` (kept as a thin compat
entrypoint): a multi-pass linter with

- a **rule registry** — every check is a ``Rule`` subclass with a stable
  id (``GL-*``), a rationale, and an embedded must-fail fixture that the
  self-test harness (``--self-test``) proves fires;
- an **interprocedural dataflow engine** (tools/graftlint/dataflow.py) —
  package-wide function table, call resolution, device-taint
  propagation across assignments / call arguments / return summaries /
  helper parameters, and bounded call-graph reachability; conservative
  at unknown provenance;
- **inline suppressions** — ``# graftlint: disable=GL-SYNC -- reason``
  on (or immediately above) the offending line; the reason is mandatory
  and a reasonless disable is itself a finding (GL-SUPPRESS) that does
  NOT suppress anything;
- a **committed baseline** (``tools/graftlint/baseline.json``) for
  grandfathered findings — new code must lint clean, old findings are
  pinned so they can only shrink;
- human and ``--json`` output (with per-rule wall seconds),
  ``--list-rules`` / ``--rule`` selection;
- configuration in one place: the ``[tool.graftlint]`` table in
  pyproject.toml — and GL-CONFIG flags any entry that stops matching
  the code (allowlists cannot rot).

Rule catalog (docs/static_analysis.md has the full rationale):

=============  ========================================================
GL-IMPORT      ``from pkg.mod import NAME`` — NAME must exist there
GL-ATTR        ``mod.NAME`` on package modules — NAME must be bound
GL-ARITY       call arity / keyword validity for resolvable calls
GL-SYNC        no host sync (explicit OR implicit) in the continuous
               batcher outside sanctioned sync points; taint survives
               helper extraction
GL-TRACE       no Python side effects inside jit-traced bodies
GL-RETRACE     jit call sites: static args bounded (pow2-bucketed),
               traced args never bare host scalars
GL-REFCOUNT    allocator acquires must reach a release on all paths
GL-COMMIT      fresh device state bound to persistent attrs must be
               mesh-committed at creation (the double-compile class)
GL-DONATE      donated buffers must be snapshotted before any stored
               alias (the use-after-donate class)
GL-ATOMIC      package file writes route through a sanctioned atomic
               discipline (the torn-state class)
GL-LIFECYCLE   every slot exit reaches the shared release surgery; no
               hand-rolled ownership writes
GL-SUPPRESS    suppression hygiene (reason mandatory, ids must exist)
GL-CONFIG      [tool.graftlint] entries must match indexed code
=============  ========================================================

Usage::

    python -m tools.graftlint                  # lint the repo, exit 1 on findings
    python -m tools.graftlint --list-rules
    python -m tools.graftlint --rule GL-SYNC --json
    python -m tools.graftlint --self-test      # every rule fires on its fixture
"""

from __future__ import annotations

from tools.graftlint.core import (  # noqa: F401
    Finding,
    LintResult,
    Rule,
    all_rules,
    get_rule,
    register,
    run,
)
from tools.graftlint.config import GraftlintConfig, load_config  # noqa: F401

# Importing the rules package registers every rule.
from tools.graftlint import rules as _rules  # noqa: E402,F401
