"""graftlint — rule-registry static analysis for JAX serving-path
discipline (pure stdlib ``ast``; mypy/ruff are not installable here).

The framework generalizes ``tools/astlint.py`` (kept as a thin compat
entrypoint): a multi-pass linter with

- a **rule registry** — every check is a ``Rule`` subclass with a stable
  id (``GL-*``), a rationale, and an embedded must-fail fixture that the
  self-test harness (``--self-test``) proves fires;
- **inline suppressions** — ``# graftlint: disable=GL-SYNC -- reason``
  on (or immediately above) the offending line; the reason is mandatory
  and a reasonless disable is itself a finding (GL-SUPPRESS) that does
  NOT suppress anything;
- a **committed baseline** (``tools/graftlint/baseline.json``) for
  grandfathered findings — new code must lint clean, old findings are
  pinned so they can only shrink;
- human and ``--json`` output, ``--list-rules`` / ``--rule`` selection;
- configuration in one place: the ``[tool.graftlint]`` table in
  pyproject.toml (sync allowlist, signature-preserving decorators,
  device-value names, bucketer functions, refcount scope).

Rule catalog (docs/static_analysis.md has the full rationale):

=============  ========================================================
GL-IMPORT      ``from pkg.mod import NAME`` — NAME must exist there
GL-ATTR        ``mod.NAME`` on package modules — NAME must be bound
GL-ARITY       call arity / keyword validity for resolvable calls
GL-SYNC        no host sync (explicit OR implicit) in the continuous
               batcher outside sanctioned sync points
GL-TRACE       no Python side effects inside jit-traced bodies
GL-RETRACE     jit call sites: static args bounded (pow2-bucketed),
               traced args never bare host scalars
GL-REFCOUNT    allocator acquires must reach a release on all paths
GL-SUPPRESS    suppression hygiene (reason mandatory, ids must exist)
=============  ========================================================

Usage::

    python -m tools.graftlint                  # lint the repo, exit 1 on findings
    python -m tools.graftlint --list-rules
    python -m tools.graftlint --rule GL-SYNC --json
    python -m tools.graftlint --self-test      # every rule fires on its fixture
"""

from __future__ import annotations

from tools.graftlint.core import (  # noqa: F401
    Finding,
    LintResult,
    Rule,
    all_rules,
    get_rule,
    register,
    run,
)
from tools.graftlint.config import GraftlintConfig, load_config  # noqa: F401

# Importing the rules package registers every rule.
from tools.graftlint import rules as _rules  # noqa: E402,F401
