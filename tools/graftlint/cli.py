"""Command-line front end. ``python -m tools.graftlint --help``."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.graftlint import core


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "rule-registry static analysis for JAX serving-path "
            "discipline (stdlib ast; see docs/static_analysis.md)"
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the repo's standard roots)",
    )
    ap.add_argument(
        "--rule",
        action="append",
        metavar="ID",
        help="run only this rule id (repeatable, comma-separable)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--baseline",
        default=str(core.BASELINE_PATH),
        help="baseline file (grandfathered findings); 'none' disables",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline with the current findings and exit 0",
    )
    ap.add_argument(
        "--self-test",
        action="store_true",
        help="prove every registered rule fires on its embedded fixture",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        rules = core.all_rules()
        width = max(len(r) for r in rules)
        for rule_id in sorted(rules):
            rule = rules[rule_id]
            print(f"{rule_id:<{width}}  {rule.title}")
        return 0

    selected: list[str] | None = None
    if args.rule:
        selected = [
            r.strip() for spec in args.rule for r in spec.split(",") if r.strip()
        ]

    if args.self_test:
        try:
            failures = core.self_test(selected)
        except KeyError as e:
            print(e.args[0], file=sys.stderr)
            return 2
        for f in failures:
            print(f, file=sys.stderr)
        print(
            f"graftlint self-test: "
            f"{len(core.all_rules() if selected is None else selected) - len(failures)}"
            f" rule(s) live, {len(failures)} dead",
            file=sys.stderr,
        )
        return 1 if failures else 0

    baseline = (
        None if args.baseline == "none" else Path(args.baseline)
    )
    try:
        result = core.run(
            args.paths or None, rules=selected, baseline=baseline
        )
    except SyntaxError as e:
        print(f"syntax error: {e}", file=sys.stderr)
        return 2
    except (KeyError, ValueError) as e:
        # Unknown rule ids, malformed [tool.graftlint] table, bad
        # baseline version — configuration errors, exit 2.
        print(e.args[0] if e.args else str(e), file=sys.stderr)
        return 2

    if args.update_baseline:
        if baseline is None:
            print("--update-baseline needs a baseline path", file=sys.stderr)
            return 2
        core.write_baseline(
            baseline, result.findings + result.baselined
        )
        print(
            f"baseline: {len(result.findings) + len(result.baselined)} "
            f"entr(y/ies) written to {baseline}",
            file=sys.stderr,
        )
        return 0

    if args.json:
        print(json.dumps(result.to_json(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
    print(
        f"graftlint: {len(result.findings)} finding(s) over "
        f"{result.n_files} files "
        f"({result.n_checked_calls} call sites arity-checked, "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined)",
        file=sys.stderr,
    )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
