"""``[tool.graftlint]`` configuration, read from pyproject.toml.

Python here is 3.10 (no stdlib ``tomllib``) and third-party TOML readers
are not installable, so this module carries a deliberately small reader
for the subset pyproject actually uses: ``key = value`` pairs inside one
table, where value is a string, integer, boolean, or a (possibly
multi-line) array of strings. That subset is a hard contract — the
reader raises on anything it does not understand rather than guessing.

Every knob has a code default equal to the committed pyproject value, so
the linter still runs (e.g. on a fixture tree in a tempdir) when no
pyproject is present.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class LockGuard:
    """One decoded ``lock_guards`` entry: a declared lock, the
    attribute aliases that count as holding it, and the state it
    guards. ``classname`` is "" for module-level locks; ``guarded``
    names instance attributes (class locks) or module globals."""

    module: str
    classname: str
    lock_attr: str
    aliases: tuple[str, ...]  # includes lock_attr itself
    guarded: tuple[str, ...]

    @property
    def name(self) -> str:
        """Canonical lock name — matches the runtime lockdep wrapper
        name so the static order graph and the sanitizer's violation
        reports speak one vocabulary."""
        if self.classname:
            return f"{self.classname}.{self.lock_attr}"
        return f"{self.module.rsplit('.', 1)[-1]}.{self.lock_attr}"


@dataclass
class GraftlintConfig:
    # Root package the domain rules reason about.
    package: str = "adversarial_spec_tpu"
    # Decorators that keep the wrapped function's calling convention
    # (GL-ARITY skips functions under anything else). Hoisted from
    # astlint's _SIG_PRESERVING.
    sig_preserving_decorators: list[str] = field(
        default_factory=lambda: [
            "jax.jit",
            "jit",
            "functools.lru_cache",
            "lru_cache",
            "functools.cache",
            "functools.wraps",
            "staticmethod",
            "classmethod",
            "contextmanager",
            "contextlib.contextmanager",
            "dataclass",
            "dataclasses.dataclass",
            "abstractmethod",
            "abc.abstractmethod",
            "pytest.fixture",
            "override",
        ]
    )
    # --- GL-SYNC -----------------------------------------------------
    # The class whose methods must not sync the host outside sanctioned
    # points (every indexed module is scanned for it), and the methods
    # allowed to sync blanket-style (hoisted from astlint's
    # _SCHEDULER_SYNC_ALLOWLIST).
    sync_class: str = "ContinuousBatcher"
    sync_allowlist: list[str] = field(
        default_factory=lambda: ["_advance_admission", "_drive_legacy"]
    )
    # Attribute names whose values live on device inside the sync class
    # (``self.active``, ``adm.pads`` …): an np.asarray / int() / bool()
    # / .item() touching any of these is an implicit host sync.
    sync_device_attrs: list[str] = field(
        default_factory=lambda: [
            "pool",
            "page_table",
            "cur_tok",
            "cur_len",
            "pad_lens",
            "n_emitted",
            "max_new",
            "active",
            "out_buf",
            "last_logits",
            "pads",
        ]
    )
    # Bare local names that hold device values in the sync class but
    # whose provenance the dataflow engine cannot derive. Since the
    # interprocedural port this list holds ONLY the pipelined double
    # buffer's entry elements: the tuples round-trip through a deque
    # (an opaque container the flow analysis does not model), so the
    # unpacked refs in _fetch_entry are seeded by hand. Everything the
    # list used to carry because taint died at an assignment or a call
    # boundary (first, adm_logits, spec_counts, demote_kv, promo_kv) is
    # now DERIVED — see tools/graftlint/dataflow.py.
    sync_device_names: list[str] = field(
        default_factory=lambda: [
            "active_ref",
            "emitted_ref",
            "out_ref",
        ]
    )
    # Bounded depth for the interprocedural passes: summary recursion,
    # call-site→parameter taint seeding rounds, and call-graph
    # reachability hops.
    dataflow_depth: int = 4
    # --- GL-TRACE ----------------------------------------------------
    # Dotted-call prefixes that are host side effects inside a traced
    # body (a trace-time call silently bakes a constant into the
    # compiled program and never runs again).
    trace_impure_calls: list[str] = field(
        default_factory=lambda: [
            "time.",
            "print",
            "input",
            "open",
            "os.environ",
            "injector.fire",
            "faults.record",
            "interleave_mod.stats.",
            "prefix_mod.stats.",
            "stats.record_",
            "random.random",
            "random.randint",
            # Observability (adversarial_spec_tpu/obs): event appends
            # and metric observes are host side effects — inside a
            # traced body they would fire once per compile shape.
            "obs.",
            "obs_mod.",
            "recorder.append",
            "metrics.",
            # Causal tracing (obs/trace.py): ambient-scope mutation and
            # span minting are host side effects — at trace time they
            # would stamp one compile's ids onto every later dispatch.
            "trace.",
            "trace_mod.",
            "trace_scope",
            "slo_check",
            # Streaming (engine/streaming.py): consumer delivery and
            # cancel accounting are host side effects — inside a traced
            # body they would fire once per compile shape, and a
            # trace-time consumer callback could never cancel anything.
            "stream_mod.",
        ]
    )
    # Extra dotted function names (module.func) to treat as trace roots
    # beyond what jit/pallas_call discovery finds. The fused serving
    # kernels are pinned so a refactor that indirects the pallas_call
    # kernel reference cannot silently drop their GL-TRACE coverage;
    # quant.matmul/unpack_int4 likewise, now that the forwards reach
    # them through an ``mm=`` parameter the callee resolver can't
    # follow.
    trace_extra_roots: list[str] = field(
        default_factory=lambda: [
            "adversarial_spec_tpu.ops.pallas_quant._qmm_int8_kernel",
            "adversarial_spec_tpu.ops.pallas_quant._qmm_int4_kernel",
            "adversarial_spec_tpu.ops.pallas_paged._paged_mq_attn_kernel",
            "adversarial_spec_tpu.ops.quant.matmul",
            "adversarial_spec_tpu.ops.quant.unpack_int4",
        ]
    )
    # --- GL-RETRACE --------------------------------------------------
    # Functions that bound a Python scalar to a small fixed set of
    # values (pow2 buckets): their results may feed static args.
    # _plan_blocks buckets fused quant-matmul block shapes to a fixed
    # candidate table (ops/pallas_quant.py).
    retrace_bucketers: list[str] = field(
        default_factory=lambda: [
            "bucket_length",
            "_next_chunk_len",
            "_fused_chunk_len",
            "_plan_blocks",
        ]
    )
    # --- GL-REFCOUNT -------------------------------------------------
    # Modules whose PageAllocator call sites get path analysis, and the
    # acquire->release pairs ("acquire=release").
    refcount_modules: list[str] = field(
        default_factory=lambda: [
            "adversarial_spec_tpu.engine.scheduler",
            "adversarial_spec_tpu.engine.prefix_cache",
            "adversarial_spec_tpu.engine.tpu",
            "adversarial_spec_tpu.engine.mock",
        ]
    )
    # swap_pin marks a page as the target of an in-flight tier swap
    # (host->device promotion scatter): a raise between pin and unpin
    # would leave the allocator convinced a swap is forever in flight
    # (and _release refusing to free the page) — the demote/promote
    # release-path discipline, statically enforced.
    # acquire_weights pins a model's weights against demotion for the
    # duration of its serve (engine/weightres.py): a raise between pin
    # and unpin would leave the model unevictable forever — the weight
    # residency release-path discipline, statically enforced.
    refcount_pairs: list[str] = field(
        default_factory=lambda: [
            "new_sequence=free_sequence",
            "adopt=free_sequence",
            "cache_ref=cache_unref",
            "swap_pin=swap_unpin",
            "acquire_weights=release_weights",
        ]
    )

    # --- GL-COMMIT ---------------------------------------------------
    # Classes whose persistent device attributes must be committed to
    # the mesh sharding at creation, the attribute names, the calls
    # that CREATE fresh (uncommitted) device state, the sanctioned
    # committing wrappers, and holder constructors whose keyword args
    # are persistent sinks (_Admission(cache=...)). ``pool`` is
    # deliberately NOT in commit_attrs: its placement is owned by the
    # paged kernels (init_page_pool), not the replicated row-state
    # sharding.
    commit_classes: list[str] = field(
        default_factory=lambda: ["ContinuousBatcher"]
    )
    commit_attrs: list[str] = field(
        default_factory=lambda: [
            "page_table",
            "cur_tok",
            "cur_len",
            "pad_lens",
            "n_emitted",
            "max_new",
            "active",
            "out_buf",
            "ctx_buf",
            "ctx_len",
            "prev_tok",
            "cache",
        ]
    )
    commit_creators: list[str] = field(
        default_factory=lambda: [
            "init_cache",
            "jnp.zeros",
            "jnp.ones",
            "jnp.full",
            "jnp.arange",
            "jnp.asarray",
            "jnp.array",
        ]
    )
    commit_wrappers: list[str] = field(
        default_factory=lambda: ["_commit", "device_put"]
    )
    commit_holders: list[str] = field(
        default_factory=lambda: ["_Admission"]
    )
    # --- GL-DONATE ---------------------------------------------------
    # Calls that take an independent snapshot of a buffer (reading the
    # snapshot after the original was donated is safe).
    donate_snapshots: list[str] = field(
        default_factory=lambda: [
            "copy",
            "jnp.copy",
            "np.copy",
            "np.array",
            "np.asarray",
            "deepcopy",
        ]
    )
    # --- GL-ATOMIC ---------------------------------------------------
    # The sanctioned write implementations (module:func or
    # module:Class.method): every other file write inside the package
    # must route through one of them.
    atomic_funcs: list[str] = field(
        default_factory=lambda: [
            "adversarial_spec_tpu.obs.events:atomic_write_text",
            "adversarial_spec_tpu.debate.journal:RoundJournal._write",
            "adversarial_spec_tpu.engine.kvtier:DiskStore.put",
            # The fleet worker's stderr log: an OS-owned append stream
            # opened once at spawn for post-mortems — a torn line in a
            # crash log is evidence, not corruption.
            "adversarial_spec_tpu.fleet.replica:WorkerReplica._spawn",
        ]
    )
    # --- GL-LIFECYCLE ------------------------------------------------
    # The slot state machine: every exit path must reach the shared
    # release surgery, and the slot-ownership attributes may only be
    # written by the surgery, the acquisition path, and the listed
    # mutators (plus __init__).
    lifecycle_class: str = "ContinuousBatcher"
    lifecycle_release: str = "_release_slot"
    lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "_finish_slot",
            "_evict_slot",
            "_cancel_slot",
            "_expire_request_deadlines",
        ]
    )
    lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: [
            "_slot_req",
            "_slot_seq",
            "_slot_consumer",
            "_slot_streamed",
            "_slot_gen",
        ]
    )
    lifecycle_mutators: list[str] = field(
        default_factory=lambda: ["_finish_admission", "_deliver_stream"]
    )
    # The fleet router's replica state machine (fleet/router.py), the
    # second GL-LIFECYCLE machine: every path that takes a replica out
    # of service (transport death, heartbeat miss, shutdown) must reach
    # the one retirement surgery, and the dead-replica ledger is
    # written nowhere else. "" disables the machine (fixture trees).
    fleet_lifecycle_class: str = "FleetRouter"
    fleet_lifecycle_release: str = "_retire_replica"
    fleet_lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "_on_replica_fault",
            "_heartbeat_failure",
            "shutdown",
        ]
    )
    fleet_lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: ["_dead"]
    )
    fleet_lifecycle_mutators: list[str] = field(default_factory=list)
    # The serve daemon's request state machine (serve/sched.py), the
    # third GL-LIFECYCLE machine: every unit exit (finish, mid-round
    # quota shed, tier preemption, drain) must reach the one release
    # surgery, and the running-set ledger is written only by the
    # surgery and the acquisition. "" disables (fixture trees).
    serve_lifecycle_class: str = "ServeScheduler"
    serve_lifecycle_release: str = "_release_unit"
    serve_lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "_finish_unit",
            "_shed_unit",
            "_preempt_unit",
            "_drain_unit",
            "drain_cancelled",
        ]
    )
    serve_lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: ["_running"]
    )
    serve_lifecycle_mutators: list[str] = field(
        default_factory=lambda: ["_start_unit"]
    )
    # The weight-residency ledger's model state machine
    # (engine/weightres.py), the fourth GL-LIFECYCLE machine: every
    # path that takes a model out of its residency state (demotion,
    # promotion's host-side consume, free, teardown) must reach the one
    # retirement surgery, and the entries ledger is written only by the
    # surgery and the _admit_model acquisition. "" disables (fixtures).
    weightres_lifecycle_class: str = "WeightLedger"
    weightres_lifecycle_release: str = "_retire_model"
    weightres_lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "demote_model",
            "promote_model",
            "free_model",
            "clear",
        ]
    )
    weightres_lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: ["_entries"]
    )
    weightres_lifecycle_mutators: list[str] = field(
        default_factory=lambda: ["_admit_model"]
    )
    # The autoscaler's replica-membership state machine
    # (fleet/autoscale.py), the fifth GL-LIFECYCLE machine: every
    # terminal transition (aborted warm-up, planned scale-in, orderly
    # shutdown) must reach the one decommission surgery, and the
    # member-state ledger is written only by the surgery and the
    # sanctioned mutators. "" disables (fixture trees).
    autoscale_lifecycle_class: str = "Autoscaler"
    autoscale_lifecycle_release: str = "_decommission"
    autoscale_lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "_abort_warm",
            "_finish_scale_in",
            "shutdown",
        ]
    )
    autoscale_lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: ["_members"]
    )
    autoscale_lifecycle_mutators: list[str] = field(
        default_factory=lambda: ["_begin_provision", "_advance"]
    )
    # The cross-replica KV handoff ledger (fleet/handoff.py), the sixth
    # GL-LIFECYCLE machine: every terminal transition (adopt, degrade,
    # abandon) must reach the one publication surgery, and the
    # terminal-outcome ledger is written nowhere else — so a handoff
    # can neither be double-counted nor vanish between states. The
    # non-terminal ``note_*`` helpers mutate the in-flight record, not
    # the owned ledger, so they need no mutator entry. "" disables
    # (fixture trees).
    handoff_lifecycle_class: str = "HandoffLedger"
    handoff_lifecycle_release: str = "_publish_blocks"
    handoff_lifecycle_exits: list[str] = field(
        default_factory=lambda: [
            "_finish_adopt",
            "_degrade",
            "_abandon",
        ]
    )
    handoff_lifecycle_owned_attrs: list[str] = field(
        default_factory=lambda: ["_outcomes"]
    )
    handoff_lifecycle_mutators: list[str] = field(default_factory=list)
    # -- GL-LOCK (rules/locking.py) ------------------------------------
    # The lock-discipline map: one entry per declared lock, both the
    # guards table (GL-LOCK-GUARD) and the lock *inventory* GL-CONFIG
    # checks declarations against. Entry grammar (TOML-subset has no
    # tables, so each entry is one string):
    #   "<module>:<Class>.<lockattr>[|<alias>...]=<attr>,<attr>"
    #   "<module>:<globalname>[|<alias>...]=<global>,<global>"
    # Aliases name other attributes holding the SAME lock (a Condition
    # constructed over it: ``with self._cond`` == holding ``_lock``).
    # An empty right-hand side declares a pure ordering lock guarding
    # no named state.
    lock_guards: list[str] = field(
        default_factory=lambda: [
            "adversarial_spec_tpu.serve.sched:ServeScheduler._lock|_cond="
            "_queues,_passes,_running,_reserved,_reserved_prefill,"
            "_debate_tenant,_debate_models,_outstanding,_quota,"
            "_capacity_fn,brownout,_prev_gamma,draining,_drain_forced,"
            "_stopped,_charged_tokens",
            "adversarial_spec_tpu.fleet.autoscale:Autoscaler._lock="
            "_members,_pending,_out_streak,_in_streak,_out_streaks,"
            "_in_streaks,_last_change_t,_last_backlog,_desired",
            "adversarial_spec_tpu.fleet.router:FleetRouter._mlock="
            "_ring,_dead,_inflight,_rr",
            "adversarial_spec_tpu.engine.weightres:WeightLedger._lock="
            "_entries,_pre_pins,_clock",
            "adversarial_spec_tpu.engine.tpu:TpuEngine._lock="
            "_models,_inflight,_loading,_demoting",
            "adversarial_spec_tpu.engine.kvtier:DiskStore._put_lock="
            "_resident",
            "adversarial_spec_tpu.engine.dispatch:_CACHE_LOCK="
            "_ENGINE_CACHE",
            "adversarial_spec_tpu.obs.metrics:MetricsRegistry._lock="
            "_families",
            "adversarial_spec_tpu.obs.trace:_mint_lock="
            "_trace_counter,_scope_counters",
            "adversarial_spec_tpu.obs.events:FlightRecorder._lock=_buf",
            "adversarial_spec_tpu.resilience.faults:_lock=_counts",
            "adversarial_spec_tpu.resilience.injector:FaultInjector._lock="
            "fired,seam_hits",
            "adversarial_spec_tpu.resilience.injector:_active_lock=_active",
            "adversarial_spec_tpu.resilience.breaker:BreakerRegistry._lock="
            "_breakers",
            "adversarial_spec_tpu.resilience.breaker:_default_lock=_default",
        ]
    )
    # Thread entry points for GL-LOCK-GUARD reachability BEYOND the
    # auto-discovered ones (threading.Thread targets and Thread
    # subclass ``run``): "<module>:<func>" / "<module>:<Class>.<method>".
    # The daemon runs debates on executor threads (run_in_executor is
    # not statically resolvable) and drills drive the autoscaler's
    # ``tick`` directly.
    lock_thread_entries: list[str] = field(
        default_factory=lambda: [
            "adversarial_spec_tpu.serve.driver:run_debate",
            "adversarial_spec_tpu.fleet.autoscale:Autoscaler.tick",
        ]
    )
    # Call patterns GL-LOCK-BLOCKING refuses while any tracked lock is
    # held: a dotted pattern matches the dotted call name (suffix), a
    # bare name matches the final attribute/function segment. ``wait``
    # on an alias of a held lock's own Condition is exempt (the wait
    # RELEASES that lock); waiting on anything else while holding a
    # lock is the finding.
    lock_blocking_calls: list[str] = field(
        default_factory=lambda: [
            "time.sleep",
            "_sleep",
            "os.fsync",
            "fsync",
            "subprocess.run",
            "subprocess.check_output",
            "subprocess.Popen",
            "block_until_ready",
            "device_get",
            "chat",
            "wait",
            "join",
        ]
    )

    def parsed_lock_guards(self) -> list["LockGuard"]:
        """``lock_guards`` decoded into :class:`LockGuard` records.
        Raises ValueError on malformed entries (GL-CONFIG surfaces the
        same failure as a finding on full runs)."""
        out: list[LockGuard] = []
        for entry in self.lock_guards:
            head, sep, attrs = entry.partition("=")
            if not sep:
                raise ValueError(
                    f"lock_guards entry {entry!r}: missing '=' "
                    "(use '<module>:<lock>=<attr>,...')"
                )
            module, msep, lockpart = head.partition(":")
            module = module.strip()
            if not msep or not module or not lockpart.strip():
                raise ValueError(
                    f"lock_guards entry {entry!r}: head must be "
                    "'<module>:<lock>'"
                )
            names = [n.strip() for n in lockpart.split("|") if n.strip()]
            first = names[0]
            if "." in first:
                classname, lock_attr = first.split(".", 1)
            else:
                classname, lock_attr = "", first
            aliases = [lock_attr]
            for n in names[1:]:
                aliases.append(n.split(".", 1)[1] if "." in n else n)
            guarded = tuple(
                a.strip() for a in attrs.split(",") if a.strip()
            )
            out.append(
                LockGuard(
                    module=module,
                    classname=classname,
                    lock_attr=lock_attr,
                    aliases=tuple(aliases),
                    guarded=guarded,
                )
            )
        return out

    def parsed_thread_entries(self) -> list[tuple[str, str, str]]:
        """``lock_thread_entries`` decoded as (module, classname, func);
        classname is "" for module-level functions."""
        out: list[tuple[str, str, str]] = []
        for entry in self.lock_thread_entries:
            module, sep, func = entry.partition(":")
            if not sep or not module.strip() or not func.strip():
                raise ValueError(
                    f"lock_thread_entries entry {entry!r}: use "
                    "'<module>:<func>' or '<module>:<Class>.<method>'"
                )
            func = func.strip()
            if "." in func:
                classname, func = func.split(".", 1)
            else:
                classname = ""
            out.append((module.strip(), classname, func))
        return out

    def named_lifecycle_machines(
        self,
    ) -> list[tuple[str, tuple[str, str, list, list, list]]]:
        """Every configured GL-LIFECYCLE machine with its knob-name
        prefix: (prefix, (class, release, exits, owned attrs,
        mutators)). Empty class names disable a machine (fixture
        trees). GL-CONFIG validates every machine through this one
        list — adding a fourth machine is one entry here plus its
        config fields."""
        machines = [
            (
                "lifecycle",
                (
                    self.lifecycle_class,
                    self.lifecycle_release,
                    self.lifecycle_exits,
                    self.lifecycle_owned_attrs,
                    self.lifecycle_mutators,
                ),
            ),
            (
                "fleet_lifecycle",
                (
                    self.fleet_lifecycle_class,
                    self.fleet_lifecycle_release,
                    self.fleet_lifecycle_exits,
                    self.fleet_lifecycle_owned_attrs,
                    self.fleet_lifecycle_mutators,
                ),
            ),
            (
                "serve_lifecycle",
                (
                    self.serve_lifecycle_class,
                    self.serve_lifecycle_release,
                    self.serve_lifecycle_exits,
                    self.serve_lifecycle_owned_attrs,
                    self.serve_lifecycle_mutators,
                ),
            ),
            (
                "weightres_lifecycle",
                (
                    self.weightres_lifecycle_class,
                    self.weightres_lifecycle_release,
                    self.weightres_lifecycle_exits,
                    self.weightres_lifecycle_owned_attrs,
                    self.weightres_lifecycle_mutators,
                ),
            ),
            (
                "autoscale_lifecycle",
                (
                    self.autoscale_lifecycle_class,
                    self.autoscale_lifecycle_release,
                    self.autoscale_lifecycle_exits,
                    self.autoscale_lifecycle_owned_attrs,
                    self.autoscale_lifecycle_mutators,
                ),
            ),
            (
                "handoff_lifecycle",
                (
                    self.handoff_lifecycle_class,
                    self.handoff_lifecycle_release,
                    self.handoff_lifecycle_exits,
                    self.handoff_lifecycle_owned_attrs,
                    self.handoff_lifecycle_mutators,
                ),
            ),
        ]
        return [m for m in machines if m[1][0]]

    def lifecycle_machines(self) -> list[tuple[str, str, list, list, list]]:
        """The configured GL-LIFECYCLE state machines as (class,
        release, exits, owned attrs, mutators); empty class names
        disable a machine."""
        return [m for _, m in self.named_lifecycle_machines()]

    def acquire_release(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for pair in self.refcount_pairs:
            acquire, _, release = pair.partition("=")
            if not release:
                raise ValueError(
                    f"refcount_pairs entry {pair!r} is not 'acquire=release'"
                )
            out[acquire.strip()] = release.strip()
        return out


_STRING = re.compile(r'^"((?:[^"\\]|\\.)*)"$')


def _parse_scalar(text: str, key: str):
    text = text.strip()
    m = _STRING.match(text)
    if m:
        return m.group(1).replace('\\"', '"').replace("\\\\", "\\")
    if text in ("true", "false"):
        return text == "true"
    if re.fullmatch(r"-?\d+", text):
        return int(text)
    raise ValueError(f"[tool.graftlint] {key}: unsupported value {text!r}")


def _parse_array(text: str, key: str) -> list:
    inner = text.strip()
    assert inner.startswith("[") and inner.endswith("]")
    items = []
    # Split on commas outside quotes — values are plain strings/ints.
    for piece in re.findall(r'"(?:[^"\\]|\\.)*"|[^,\[\]\s]+', inner[1:-1]):
        items.append(_parse_scalar(piece, key))
    return items


def _strip_comment(line: str) -> str:
    """Drop a ``#`` comment that is OUTSIDE any double-quoted string
    (valid TOML allows inline comments after values and whole comment
    lines inside multi-line arrays)."""
    out = []
    in_string = False
    escaped = False
    for ch in line:
        if escaped:
            out.append(ch)
            escaped = False
            continue
        if in_string and ch == "\\":
            out.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_string = not in_string
        elif ch == "#" and not in_string:
            break
        out.append(ch)
    return "".join(out)


def read_graftlint_table(pyproject: Path) -> dict:
    """The ``[tool.graftlint]`` table as a plain dict (subset reader)."""
    raw: dict = {}
    if not pyproject.exists():
        return raw
    in_table = False
    pending_key: str | None = None
    pending_val = ""
    for line in pyproject.read_text(encoding="utf-8").splitlines():
        stripped = _strip_comment(line).strip()
        if pending_key is not None:
            pending_val += " " + stripped
            if pending_val.count("[") == pending_val.count("]"):
                raw[pending_key] = _parse_array(pending_val, pending_key)
                pending_key = None
            continue
        if stripped.startswith("["):
            in_table = stripped == "[tool.graftlint]"
            continue
        if not in_table or not stripped or stripped.startswith("#"):
            continue
        key, _, value = stripped.partition("=")
        key, value = key.strip(), value.strip()
        if value.startswith("["):
            if value.count("[") == value.count("]"):
                raw[key] = _parse_array(value, key)
            else:
                pending_key, pending_val = key, value
        else:
            raw[key] = _parse_scalar(value, key)
    return raw


def load_config(repo: Path) -> GraftlintConfig:
    cfg = GraftlintConfig()
    raw = read_graftlint_table(repo / "pyproject.toml")
    for key, value in raw.items():
        attr = key.replace("-", "_")
        if not hasattr(cfg, attr):
            raise ValueError(f"[tool.graftlint] unknown key {key!r}")
        setattr(cfg, attr, value)
    return cfg


def config_drift(repo: Path) -> list[str]:
    """Field-by-field drift between pyproject's ``[tool.graftlint]``
    table and the in-code defaults (which exist so fixture trees lint
    without a pyproject — they must never diverge from the committed
    table). THE shared drift guard: tools/lint_all.py runs it as a
    preflight stage and tests/test_tools.py pins it empty; per-module
    copies of the same check are retired."""
    import dataclasses

    cfg = load_config(repo)
    dflt = GraftlintConfig()
    out: list[str] = []
    for f in dataclasses.fields(cfg):
        have, want = getattr(cfg, f.name), getattr(dflt, f.name)
        if have != want:
            out.append(
                f"{f.name}: pyproject={have!r} != code default={want!r}"
            )
    return out
