"""Rule registry, suppression/baseline machinery, and the lint driver.

Execution model (multi-pass):

1. collect files under the requested roots;
2. **index pass** — parse every file once into ``ModuleInfo``
   (tools/graftlint/index.py);
3. **rule passes** — each selected rule walks the index and reports
   findings through ``Context.report``;
4. **filter pass** — inline suppressions (reason mandatory) and the
   committed baseline partition raw findings into reported / suppressed
   / baselined; malformed suppressions become GL-SUPPRESS findings.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

from tools.graftlint.config import GraftlintConfig, load_config
from tools.graftlint.index import ModuleInfo, build_index, modname_for

REPO = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"
BASELINE_VERSION = 1
JSON_VERSION = 1

DEFAULT_ROOTS = (
    "adversarial_spec_tpu",
    "tools",
    "tests",
    "bench.py",
    "__graft_entry__.py",
    "tpu_ladder.py",
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    def fingerprint(self) -> tuple[str, str, str]:
        """Line-number-free identity used for baseline matching: survives
        unrelated edits shifting the file."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class Rule:
    """One registered check. Subclasses set the class attributes and
    implement ``check``; ``fixtures`` maps relative paths to source for
    a minimal tree on which the rule MUST fire (the self-test gate —
    a rule that cannot fail is not a rule)."""

    id: str = ""
    title: str = ""
    rationale: str = ""
    fixtures: dict[str, str] = {}
    # Config overrides the self-test applies when linting the fixture
    # (e.g. pointing refcount_modules at the fixture tree's modules).
    fixture_config: dict = {}

    def check(self, ctx: "Context") -> None:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or not re.fullmatch(r"GL-[A-Z]+(-[A-Z]+)*", cls.id):
        raise ValueError(f"rule id {cls.id!r} must match GL-[A-Z]+(-[A-Z]+)*")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> dict[str, Rule]:
    return dict(_REGISTRY)


def get_rule(rule_id: str) -> Rule:
    return _REGISTRY[rule_id]


class Context:
    """Everything a rule pass sees: repo root, config, module index.
    ``full_run`` is True when the default roots (the whole repo) are
    being linted — rules that prove absence over the package
    (GL-CONFIG's stale-entry check) only run then; a ``--changed``
    subset cannot prove anything absent."""

    def __init__(
        self,
        repo: Path,
        cfg: GraftlintConfig,
        index: dict[str, ModuleInfo],
        full_run: bool = True,
    ):
        self.repo = repo
        self.cfg = cfg
        self.index = index
        self.full_run = full_run
        self.findings: list[Finding] = []
        self.n_checked_calls = 0  # GL-ARITY call sites verified
        # Rule-published structured output surfaced in --json (e.g.
        # GL-LOCK-ORDER's discovered lock hierarchy). Keyed by a short
        # artifact name; values must be JSON-serializable.
        self.artifacts: dict[str, object] = {}

    def report(
        self, rule_id: str, path: Path, lineno: int, message: str
    ) -> None:
        try:
            rel = path.relative_to(self.repo).as_posix()
        except ValueError:
            rel = path.as_posix()
        self.findings.append(Finding(rule_id, rel, lineno, message))

    def module(self, modname: str) -> ModuleInfo | None:
        return self.index.get(modname)


# ------------------------------------------------------------ suppression

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<ids>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?P<reason>\s+--\s+\S.*)?\s*$"
)


@dataclass
class Suppression:
    path: str
    comment_line: int
    target_line: int  # the code line the suppression covers
    ids: tuple[str, ...]
    reason: str  # "" when missing (invalid — rejected)
    used: bool = False


def parse_suppressions(path: Path, repo: Path) -> list[Suppression]:
    """Inline ``# graftlint: disable=ID[,ID...] -- reason`` comments.

    Tokenized, not grepped: only genuine COMMENT tokens count, so a
    fixture string or docstring quoting the marker never becomes a live
    suppression. A trailing comment covers its own line; a standalone
    comment line covers the next code line.
    """
    import io
    import tokenize

    rel = path.relative_to(repo).as_posix()
    source = path.read_text(encoding="utf-8")
    lines = source.splitlines()
    out: list[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        ids = tuple(s.strip() for s in m.group("ids").split(","))
        reason = (m.group("reason") or "").strip()
        reason = reason[2:].strip() if reason.startswith("--") else ""
        target = i
        if lines[i - 1].strip().startswith("#"):
            # Standalone comment: applies to the next code line.
            for j in range(i, len(lines)):
                nxt = lines[j].strip()
                if nxt and not nxt.startswith("#"):
                    target = j + 1
                    break
        out.append(
            Suppression(
                path=rel,
                comment_line=i,
                target_line=target,
                ids=ids,
                reason=reason,
            )
        )
    return out


# --------------------------------------------------------------- baseline


def load_baseline(path: Path) -> list[tuple[str, str, str]]:
    if not path.exists():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; "
            f"expected {BASELINE_VERSION}"
        )
    return [
        (e["rule"], e["path"], e["message"]) for e in data.get("entries", [])
    ]


def write_baseline(path: Path, findings: list[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    path.write_text(
        json.dumps({"version": BASELINE_VERSION, "entries": entries}, indent=1)
        + "\n",
        encoding="utf-8",
    )


# ----------------------------------------------------------------- driver


@dataclass
class LintResult:
    findings: list[Finding]  # what the caller should act on
    suppressed: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    n_files: int = 0
    n_checked_calls: int = 0
    rules_run: tuple[str, ...] = ()
    # Per-rule wall seconds: slow passes must be visible as the rule
    # set grows (interprocedural passes are not free).
    rule_seconds: dict[str, float] = field(default_factory=dict)
    # Structured rule output (Context.artifacts) — e.g. the canonical
    # lock hierarchy GL-LOCK-ORDER discovered.
    artifacts: dict[str, object] = field(default_factory=dict)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_json(self) -> dict:
        by_rule: dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "version": JSON_VERSION,
            "rules": sorted(self.rules_run),
            "findings": [f.to_dict() for f in self.findings],
            "counts": {
                "total": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "by_rule": dict(sorted(by_rule.items())),
            },
            "files": self.n_files,
            "checked_calls": self.n_checked_calls,
            "rule_seconds": {
                r: round(s, 4)
                for r, s in sorted(self.rule_seconds.items())
            },
            "artifacts": dict(sorted(self.artifacts.items())),
        }


def collect_files(roots: list[Path]) -> list[Path]:
    files: list[Path] = []
    for r in roots:
        if r.is_dir():
            files += sorted(r.rglob("*.py"))
        elif r.suffix == ".py" and r.exists():
            files.append(r)
    return files


def run(
    paths: list[str] | None = None,
    *,
    repo: Path = REPO,
    rules: list[str] | None = None,
    cfg: GraftlintConfig | None = None,
    baseline: Path | None = BASELINE_PATH,
    full: bool | None = None,
) -> LintResult:
    """Lint ``paths`` (repo-default roots when empty) with the selected
    rules (all when None). Raises SyntaxError on unparsable files.
    ``full`` marks a whole-repo run (default: True iff ``paths`` is
    empty) — absence-proving rules (GL-CONFIG) only run then."""
    import time

    cfg = cfg or load_config(repo)
    roots = (
        [Path(p).resolve() for p in paths]
        if paths
        else [repo / r for r in DEFAULT_ROOTS]
    )
    files = collect_files(roots)
    index = build_index(files, repo, set(cfg.sig_preserving_decorators))
    ctx = Context(repo, cfg, index, full_run=not paths if full is None else full)

    selected = rules if rules is not None else sorted(_REGISTRY)
    unknown = [r for r in selected if r not in _REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule id(s): {', '.join(unknown)}")
    rule_seconds: dict[str, float] = {}
    for rule_id in selected:
        t0 = time.perf_counter()
        _REGISTRY[rule_id].check(ctx)
        rule_seconds[rule_id] = time.perf_counter() - t0

    # Dedup (several taint hits can land on one line), drop findings for
    # unselected ids (shared passes may emit siblings), and sort.
    raw = sorted(
        {f for f in ctx.findings if f.rule in selected},
        key=lambda f: (f.path, f.line, f.rule),
    )

    suppressions: dict[str, list[Suppression]] = {}
    for f in files:
        rel = f.relative_to(repo).as_posix()
        suppressions[rel] = parse_suppressions(f, repo)

    reported: list[Finding] = []
    suppressed: list[Finding] = []
    for finding in raw:
        supp = None
        for s in suppressions.get(finding.path, ()):
            if finding.rule in s.ids and finding.line in (
                s.target_line,
                s.comment_line,
            ):
                supp = s
                break
        if supp is not None and supp.reason:
            supp.used = True
            suppressed.append(finding)
        else:
            reported.append(finding)

    # Suppression hygiene is itself a rule (GL-SUPPRESS): a reasonless
    # disable never suppresses, unknown ids are flagged so typos can't
    # silently disarm a rule, and a reasoned suppression that matched
    # nothing is STALE — its finding was fixed, the mute lingers.
    if rules is None or "GL-SUPPRESS" in selected:
        selected_set = set(selected)
        for file_supps in suppressions.values():
            for s in file_supps:
                if not s.reason:
                    reported.append(
                        Finding(
                            "GL-SUPPRESS",
                            s.path,
                            s.comment_line,
                            "suppression missing mandatory reason "
                            "(use: # graftlint: disable=<id> -- <reason>)",
                        )
                    )
                for rid in s.ids:
                    if rid not in _REGISTRY:
                        reported.append(
                            Finding(
                                "GL-SUPPRESS",
                                s.path,
                                s.comment_line,
                                f"suppression names unknown rule {rid!r}",
                            )
                        )
                # Stale check only when every suppressed rule actually
                # ran this invocation (a --rule subset must not call
                # the others' suppressions stale) AND the lint covered
                # the full roots — on a --changed path subset the taint
                # engine may lack the cross-module context that derives
                # a suppression's finding, and "no finding matched" on
                # a subset proves nothing (the GL-CONFIG rule's gate,
                # applied to suppressions).
                if (
                    s.reason
                    and not s.used
                    and ctx.full_run
                    and all(rid in selected_set for rid in s.ids)
                ):
                    reported.append(
                        Finding(
                            "GL-SUPPRESS",
                            s.path,
                            s.comment_line,
                            f"stale suppression ({', '.join(s.ids)}): "
                            "no finding matched it — the issue was "
                            "fixed or moved; delete the comment",
                        )
                    )

    baselined: list[Finding] = []
    if baseline is not None:
        known = set(load_baseline(baseline))
        still: list[Finding] = []
        for finding in reported:
            if finding.fingerprint() in known:
                baselined.append(finding)
            else:
                still.append(finding)
        reported = still

    reported.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(
        findings=reported,
        suppressed=suppressed,
        baselined=baselined,
        n_files=len(files),
        n_checked_calls=ctx.n_checked_calls,
        rules_run=tuple(selected),
        rule_seconds=rule_seconds,
        artifacts=dict(ctx.artifacts),
    )


def lint_sources(
    sources: dict[str, str],
    *,
    rules: list[str],
    cfg: GraftlintConfig | None = None,
    tmpdir: Path | None = None,
) -> list[Finding]:
    """Lint an in-memory tree (fixture helper for self-test + tests):
    writes ``sources`` under a temp repo root and runs the selected
    rules with no baseline."""
    import tempfile

    cfg = cfg or GraftlintConfig()
    own = tmpdir is None
    root = Path(tempfile.mkdtemp(prefix="graftlint-")) if own else tmpdir
    try:
        for rel, src in sources.items():
            dest = root / rel
            dest.parent.mkdir(parents=True, exist_ok=True)
            if (
                dest.parent != root
                and not (dest.parent / "__init__.py").exists()
            ):
                (dest.parent / "__init__.py").write_text("")
            dest.write_text(src, encoding="utf-8")
        result = run(
            [str(root)],
            repo=root,
            rules=rules,
            cfg=cfg,
            baseline=None,
            full=True,  # a fixture tree is its own whole repo
        )
        return result.findings
    finally:
        if own:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def self_test(rule_ids: list[str] | None = None) -> list[str]:
    """Prove every selected rule fires on its embedded fixture. Returns
    a list of failure messages (empty = all rules live)."""
    unknown = [r for r in (rule_ids or ()) if r not in _REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule id(s): {', '.join(unknown)}")
    failures: list[str] = []
    for rule_id in sorted(rule_ids or _REGISTRY):
        rule = _REGISTRY[rule_id]
        if not rule.fixtures:
            failures.append(f"{rule_id}: no must-fail fixture embedded")
            continue
        cfg = GraftlintConfig(**rule.fixture_config)
        findings = lint_sources(
            dict(rule.fixtures), rules=[rule_id], cfg=cfg
        )
        if not any(f.rule == rule_id for f in findings):
            failures.append(
                f"{rule_id}: fixture produced no {rule_id} finding "
                f"(got: {[f.render() for f in findings]})"
            )
    return failures


def resolve_module_path(ctx: Context, path: Path) -> str:
    return modname_for(path, ctx.repo)
