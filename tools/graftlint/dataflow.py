"""Interprocedural dataflow over the module index (graftlint v2).

The v1 rules were intraprocedural: GL-SYNC decided "is this a device
value" from two hand-maintained name lists, and the moment a batcher
method's body was extracted into a helper the taint died at the call
boundary — the lists grew an entry per refactor (``demote_kv``,
``spec_counts``, ``first`` … each existed only because the analysis
could not see one assignment or one call deep). This module supplies
the shared machinery the v2 rules (GL-SYNC, GL-COMMIT, GL-DONATE,
GL-LIFECYCLE) build on:

- **function table** — every module-level function and class method as
  a ``FuncEntry`` with a stable ``(modname, funckey)`` key;
- **call resolution** — the static target of ``name(...)``,
  ``alias.func(...)`` and ``self.method(...)`` call sites, resolved
  through the index's import maps;
- **device-taint analysis** (``DeviceTaint``) — seed taint from
  configured attribute names, then propagate through local assignments
  (tuple-sensitive), through calls whose arguments carry taint, and
  across call boundaries via bounded always-tainted return summaries
  and call-site→parameter seeding (``propagate_params``);
- **reachability** (``reaches``) — bounded-depth call-graph walks
  (GL-LIFECYCLE's "every exit path reaches ``_release_slot``").

Discipline: *conservative at unknown provenance* (GL-RETRACE's rule).
A name or call the analysis cannot resolve is UNTAINTED — the engine
exists to remove hand-maintained lists without minting false
positives; anything it cannot prove device-derived stays the job of
the (now much smaller) seed lists.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.graftlint.index import ModuleInfo, dotted_name

# Calls that CONSUME a device value and yield a host value (these are
# the syncs GL-SYNC reports; their results carry no further taint).
_SYNC_CONSUMER_BUILTINS = {"int", "float", "bool", "len"}
# Dotted-prefix producers of fresh device values.
_DEVICE_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.")


@dataclass(frozen=True)
class FuncEntry:
    """One function or method in the index."""

    modname: str
    classname: str  # "" for module-level functions
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef

    @property
    def funckey(self) -> str:
        return f"{self.classname}.{self.name}" if self.classname else self.name

    @property
    def key(self) -> tuple[str, str]:
        return (self.modname, self.funckey)

    @property
    def qualname(self) -> str:
        return f"{self.modname}:{self.funckey}"

    def param_names(self) -> tuple[str, ...]:
        a = self.node.args
        pos = [p.arg for p in a.posonlyargs + a.args]
        if self.classname and pos:
            decs = {dotted_name(d) for d in self.node.decorator_list}
            if "staticmethod" not in decs:
                pos = pos[1:]  # self / cls
        return tuple(pos) + tuple(p.arg for p in a.kwonlyargs)


def function_table(index: dict[str, ModuleInfo]) -> dict[tuple[str, str], FuncEntry]:
    """(modname, funckey) -> FuncEntry over the whole index."""
    table: dict[tuple[str, str], FuncEntry] = {}
    for modname, info in index.items():
        for name, node in info.func_nodes.items():
            table[(modname, name)] = FuncEntry(modname, "", name, node)
        for cname, ci in info.classes.items():
            for mname, mnode in ci.method_nodes.items():
                table[(modname, f"{cname}.{mname}")] = FuncEntry(
                    modname, cname, mname, mnode
                )
    return table


def resolve_call(
    info: ModuleInfo,
    call: ast.Call,
    *,
    classname: str = "",
    index: dict[str, ModuleInfo] | None = None,
) -> tuple[str, str] | None:
    """The (modname, funckey) a call's func expression statically
    names, or None. ``classname`` enables ``self.method`` resolution
    within the enclosing class."""
    f = call.func
    if isinstance(f, ast.Name):
        name = f.id
        if name in info.func_nodes:
            return (info.modname, name)
        if name in info.from_imports:
            src_mod, orig = info.from_imports[name]
            if index is None or (
                src_mod in index and orig in index[src_mod].func_nodes
            ):
                return (src_mod, orig)
        return None
    if isinstance(f, ast.Attribute):
        base = f.value
        if (
            isinstance(base, ast.Name)
            and base.id == "self"
            and classname
            and classname in info.classes
            and f.attr in info.classes[classname].method_nodes
        ):
            return (info.modname, f"{classname}.{f.attr}")
        if isinstance(base, ast.Name):
            target = info.mod_imports.get(base.id)
            if target is not None and (
                index is None
                or (target in index and f.attr in index[target].func_nodes)
            ):
                return (target, f.attr)
    return None


def bind_args(
    entry: FuncEntry, call: ast.Call
) -> list[tuple[str, ast.expr]]:
    """(param_name, arg_expr) pairs for a call's statically bindable
    arguments; *args/**kwargs entries are skipped (unknown binding)."""
    params = entry.param_names()
    bound: list[tuple[str, ast.expr]] = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            continue
        if i < len(params):
            bound.append((params[i], arg))
    for kw in call.keywords:
        if kw.arg is not None:
            bound.append((kw.arg, kw.value))
    return bound


def is_sync_consumer(call: ast.Call) -> bool:
    """True for calls that fetch a device value to host (np.asarray,
    jax.device_get, int/float/bool/len, .item(), .tolist()) — the
    result is a HOST value and carries no device taint."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _SYNC_CONSUMER_BUILTINS
    if isinstance(f, ast.Attribute):
        if f.attr in ("item", "tolist", "device_get"):
            return True
        # asarray is a consumer only off numpy (jnp.asarray PRODUCES a
        # device value).
        return (
            f.attr == "asarray"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")
        )
    return False


class DeviceTaint:
    """Device-value taint over the index, seeded by attribute / bare
    names and propagated interprocedurally (bounded depth)."""

    def __init__(
        self,
        index: dict[str, ModuleInfo],
        seed_attrs: set[str],
        seed_names: set[str],
        *,
        depth: int = 4,
    ):
        self.index = index
        self.seed_attrs = seed_attrs
        self.seed_names = seed_names
        self.depth = max(1, depth)
        self.table = function_table(index)
        # (modname, funckey) -> extra tainted parameter names, seeded by
        # propagate_params from tainted call-site arguments.
        self.param_taint: dict[tuple[str, str], set[str]] = {}
        self._envs: dict[tuple[str, str], set[str]] = {}
        self._summaries: dict[tuple[str, str], bool] = {}

    # -- per-function environments ------------------------------------

    def env(self, entry: FuncEntry) -> set[str]:
        """Tainted local names of ``entry`` (sticky, two-pass so
        loop-carried assignments converge)."""
        cached = self._envs.get(entry.key)
        if cached is not None:
            return cached
        env: set[str] = set(self.param_taint.get(entry.key, ()))
        self._envs[entry.key] = env  # publish early (recursion guard)
        info = self.index[entry.modname]
        for _ in range(2):
            for node in ast.walk(entry.node):
                self._flow_stmt(node, env, info, entry.classname)
        return env

    def _flow_stmt(
        self, node: ast.AST, env: set[str], info, classname: str
    ) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            v = node.value
            if isinstance(t, ast.Name):
                if self._expr(v, env, info, classname):
                    env.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                elts = [e for e in t.elts if isinstance(e, ast.Name)]
                if isinstance(v, (ast.Tuple, ast.List)) and len(
                    v.elts
                ) == len(t.elts):
                    # Element-wise: `cache, logits = adm.cache, adm.x`
                    # taints exactly the elements whose source is
                    # tainted, not the whole row.
                    for te, ve in zip(t.elts, v.elts):
                        if isinstance(te, ast.Name) and self._expr(
                            ve, env, info, classname
                        ):
                            env.add(te.id)
                elif self._expr(v, env, info, classname):
                    for e in elts:
                        env.add(e.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            if node.value is not None and self._expr(
                node.value, env, info, classname
            ):
                env.add(node.target.id)
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ):
            if node.target.id in env or self._expr(
                node.value, env, info, classname
            ):
                env.add(node.target.id)
        elif isinstance(node, ast.NamedExpr) and isinstance(
            node.target, ast.Name
        ):
            if self._expr(node.value, env, info, classname):
                env.add(node.target.id)

    # -- expression taint ---------------------------------------------

    def tainted(self, expr: ast.expr, entry: FuncEntry) -> bool:
        return self._expr(
            expr,
            self.env(entry),
            self.index[entry.modname],
            entry.classname,
        )

    def _expr(
        self,
        expr: ast.expr,
        env: set[str],
        info,
        classname: str,
        depth: int | None = None,
    ) -> bool:
        depth = self.depth if depth is None else depth
        if isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Name):
            return expr.id in env or expr.id in self.seed_names
        if isinstance(expr, ast.Attribute):
            if expr.attr in self.seed_attrs:
                return True
            return self._expr(expr.value, env, info, classname, depth)
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name.startswith(_DEVICE_PRODUCER_PREFIXES):
                return True
            if is_sync_consumer(expr):
                return False  # host result (the sync itself is the finding)
            # A call carrying taint in (receiver chain or any argument)
            # returns taint out — read_tokens(self.pool, …),
            # sample_tokens(last_logits, …).
            for sub in (
                [expr.func]
                + list(expr.args)
                + [kw.value for kw in expr.keywords]
            ):
                if isinstance(sub, ast.Starred):
                    sub = sub.value
                if self._expr(sub, env, info, classname, depth):
                    return True
            # Untainted args: consult the callee's return summary
            # (bounded) — self._dispatch_spec() returns device counts
            # no matter what it is passed.
            if depth > 0:
                target = resolve_call(
                    info, expr, classname=classname, index=self.index
                )
                if target is not None and target in self.table:
                    return self._summary(target, depth - 1)
            return False
        if isinstance(expr, ast.Lambda):
            return False
        # Containers, subscripts, arithmetic, comparisons,
        # comprehensions: tainted iff any sub-expression is.
        return any(
            self._expr(child, env, info, classname, depth)
            for child in ast.iter_child_nodes(expr)
            if isinstance(child, ast.expr)
        )

    def _summary(self, key: tuple[str, str], depth: int) -> bool:
        """Always-tainted return summary: does the function return a
        device-tainted value even with untainted parameters?"""
        cached = self._summaries.get(key)
        if cached is not None:
            return cached
        self._summaries[key] = False  # recursion guard
        entry = self.table[key]
        info = self.index[entry.modname]
        env: set[str] = set()
        for _ in range(2):
            for node in ast.walk(entry.node):
                self._flow_stmt(node, env, info, entry.classname)
        result = False
        for node in ast.walk(entry.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if self._expr(
                    node.value, env, info, entry.classname, depth
                ):
                    result = True
                    break
        self._summaries[key] = result
        return result

    # -- interprocedural parameter seeding ----------------------------

    def propagate_params(
        self,
        roots: list[FuncEntry],
        accept,
    ) -> list[FuncEntry]:
        """Seed helper parameters from tainted call-site arguments,
        starting at ``roots`` and following resolvable calls to entries
        ``accept(entry)`` approves, for ``self.depth`` rounds. Returns
        the helpers reached with at least one tainted parameter —
        device taint surviving helper extraction."""
        reached: dict[tuple[str, str], FuncEntry] = {}
        frontier = list(roots)
        for _ in range(self.depth):
            next_frontier: list[FuncEntry] = []
            for caller in frontier:
                info = self.index[caller.modname]
                for node in ast.walk(caller.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target = resolve_call(
                        info,
                        node,
                        classname=caller.classname,
                        index=self.index,
                    )
                    if target is None or target not in self.table:
                        continue
                    callee = self.table[target]
                    if not accept(callee):
                        continue
                    new = set()
                    for param, arg in bind_args(callee, node):
                        if self.tainted(arg, caller):
                            new.add(param)
                    have = self.param_taint.setdefault(target, set())
                    if new - have:
                        have |= new
                        self._envs.pop(target, None)  # re-derive
                    if new and target not in reached:
                        reached[target] = callee
                        next_frontier.append(callee)
            if not next_frontier:
                break
            frontier = next_frontier
        return list(reached.values())


# -- call-graph reachability ------------------------------------------


def reaches(
    index: dict[str, ModuleInfo],
    start: FuncEntry,
    target_name: str,
    *,
    depth: int = 4,
    table: dict[tuple[str, str], FuncEntry] | None = None,
) -> bool:
    """True when ``start`` transitively calls a function/method named
    ``target_name`` within ``depth`` resolvable hops (also True for a
    direct ``self.<target_name>()`` / ``<target_name>()`` call that the
    resolver cannot bind to an indexed body). Pass a prebuilt
    ``function_table`` when querying repeatedly — rebuilding it per
    query walks the whole index each time."""
    if table is None:
        table = function_table(index)
    seen: set[tuple[str, str]] = set()
    frontier = [start]
    for _ in range(depth):
        next_frontier: list[FuncEntry] = []
        for fn in frontier:
            info = index[fn.modname]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                called = (
                    f.attr
                    if isinstance(f, ast.Attribute)
                    else f.id
                    if isinstance(f, ast.Name)
                    else ""
                )
                if called == target_name:
                    return True
                tgt = resolve_call(
                    info, node, classname=fn.classname, index=index
                )
                if tgt is not None and tgt in table and tgt not in seen:
                    seen.add(tgt)
                    next_frontier.append(table[tgt])
        if not next_frontier:
            return False
        frontier = next_frontier
    return False
