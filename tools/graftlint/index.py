"""Pass 1: parse every file once and index what the rules need.

Ported from tools/astlint.py's collection phase and extended: each
``ModuleInfo`` additionally keeps its parsed tree, its import maps
(local name -> module / (module, original name)), the function AST nodes
(GL-TRACE walks bodies), and the module's jit entry points with their
``static_argnames`` (GL-RETRACE checks call sites against them).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class FuncSig:
    name: str
    n_pos: int  # positional (posonly + args), excluding self for methods
    n_pos_defaults: int
    kwonly: tuple[str, ...] = ()
    kwonly_required: tuple[str, ...] = ()
    has_vararg: bool = False
    has_kwarg: bool = False
    pos_names: tuple[str, ...] = ()
    checkable: bool = True  # False when a decorator may change the sig


@dataclass
class ClassInfo:
    name: str
    methods: dict[str, FuncSig] = field(default_factory=dict)
    bases: tuple[str, ...] = ()
    # Method AST nodes — the interprocedural passes (tools/graftlint/
    # dataflow.py) walk bodies and resolve self.method() call targets.
    method_nodes: dict[str, ast.FunctionDef] = field(default_factory=dict)


@dataclass
class JitEntry:
    """A jit-compiled callable: calling it with an unbounded Python
    scalar (static arg) or a bare host scalar (traced arg) retraces;
    calling it donates the buffers bound to ``donate_argnames`` (reading
    a donated buffer after the dispatch is use-after-free)."""

    name: str  # public callable name in its module
    modname: str
    impl: str  # the wrapped function's name (signature source)
    static_argnames: tuple[str, ...] = ()
    donate_argnames: tuple[str, ...] = ()


@dataclass
class ModuleInfo:
    path: Path
    modname: str
    tree: ast.Module = None  # type: ignore[assignment]
    bindings: set[str] = field(default_factory=set)
    functions: dict[str, FuncSig] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    func_nodes: dict[str, ast.FunctionDef] = field(default_factory=dict)
    # local alias -> imported module name   (import x.y as z)
    mod_imports: dict[str, str] = field(default_factory=dict)
    # local alias -> (source module, original name)  (from m import n)
    from_imports: dict[str, tuple[str, str]] = field(default_factory=dict)
    jit_entries: dict[str, JitEntry] = field(default_factory=dict)


def decorator_name(dec: ast.expr) -> str:
    """Best-effort dotted name of a decorator / call / base expression."""
    if isinstance(dec, ast.Call):
        inner = decorator_name(dec.func)
        if inner in ("functools.partial", "partial"):
            if dec.args:
                wrapped = decorator_name(dec.args[0])
                return wrapped if wrapped != "?" else "partial(?)"
            return "partial(?)"
        return inner
    if isinstance(dec, ast.Attribute):
        base = decorator_name(dec.value)
        return f"{base}.{dec.attr}" if base else dec.attr
    if isinstance(dec, ast.Name):
        return dec.id
    return "?"


def dotted_name(expr: ast.expr) -> str:
    """Dotted form of a Name/Attribute chain ("" when not a chain)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return f"{base}.{expr.attr}" if base else ""
    return ""


def sig_of(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
    is_method: bool,
    sig_preserving: set[str],
) -> FuncSig:
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    skip_self = 0
    if is_method:
        decs = {decorator_name(d) for d in fn.decorator_list}
        if "staticmethod" not in decs and pos:
            skip_self = 1  # self / cls
    pos = pos[skip_self:]
    checkable = True
    for d in fn.decorator_list:
        name = decorator_name(d)
        if name not in sig_preserving and not name.startswith(
            ("jax.", "functools.", "pl.", "pytest.")
        ):
            checkable = False
    kwonly = tuple(p.arg for p in a.kwonlyargs)
    kwonly_required = tuple(
        p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults) if d is None
    )
    return FuncSig(
        name=fn.name,
        n_pos=len(pos),
        n_pos_defaults=len(a.defaults),
        kwonly=kwonly,
        kwonly_required=kwonly_required,
        has_vararg=a.vararg is not None,
        has_kwarg=a.kwarg is not None,
        pos_names=tuple(pos),
        checkable=checkable,
    )


def _jit_argnames(call: ast.Call, key: str) -> tuple[str, ...]:
    """``static_argnames`` / ``donate_argnames`` tuple from a jax.jit /
    partial(jax.jit, ...) call."""
    for kw in call.keywords:
        if kw.arg == key:
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
    return ()


def _jit_call_info(
    expr: ast.expr,
) -> tuple[tuple[str, ...], tuple[str, ...], str] | None:
    """Recognize ``X = partial(jax.jit, ...)(impl)`` / ``jax.jit(impl)``
    value expressions: returns (static_argnames, donate_argnames,
    impl_name) or None."""
    if not isinstance(expr, ast.Call):
        return None
    inner = expr.func
    if isinstance(inner, ast.Call):
        head = decorator_name(inner.func)
        if head in ("functools.partial", "partial") and inner.args:
            if decorator_name(inner.args[0]) in ("jax.jit", "jit"):
                if expr.args and isinstance(expr.args[0], ast.Name):
                    return (
                        _jit_argnames(inner, "static_argnames"),
                        _jit_argnames(inner, "donate_argnames"),
                        expr.args[0].id,
                    )
    elif decorator_name(inner) in ("jax.jit", "jit"):
        if expr.args and isinstance(expr.args[0], ast.Name):
            return (
                _jit_argnames(expr, "static_argnames"),
                _jit_argnames(expr, "donate_argnames"),
                expr.args[0].id,
            )
    return None


def _jit_decoration(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> tuple[tuple[str, ...], tuple[str, ...]] | None:
    """(static_argnames, donate_argnames) when ``fn`` is jit-decorated,
    else None."""
    for dec in fn.decorator_list:
        name = decorator_name(dec)
        if name in ("jax.jit", "jit"):
            if isinstance(dec, ast.Call):
                return (
                    _jit_argnames(dec, "static_argnames"),
                    _jit_argnames(dec, "donate_argnames"),
                )
            return ((), ())
        if isinstance(dec, ast.Call):
            head = decorator_name(dec.func)
            if head in ("functools.partial", "partial") and dec.args:
                if decorator_name(dec.args[0]) in ("jax.jit", "jit"):
                    return (
                        _jit_argnames(dec, "static_argnames"),
                        _jit_argnames(dec, "donate_argnames"),
                    )
    return None


def collect_module(
    path: Path,
    modname: str,
    sig_preserving: set[str] | None = None,
) -> ModuleInfo:
    sig_preserving = sig_preserving or set()
    # filename= so a SyntaxError names the failing file, not <unknown>.
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    info = ModuleInfo(path=path, modname=modname, tree=tree)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.bindings.add(node.name)
            info.functions[node.name] = sig_of(
                node, is_method=False, sig_preserving=sig_preserving
            )
            info.func_nodes[node.name] = node
            jit = _jit_decoration(node)
            if jit is not None:
                static, donated = jit
                info.jit_entries[node.name] = JitEntry(
                    name=node.name,
                    modname=modname,
                    impl=node.name,
                    static_argnames=static,
                    donate_argnames=donated,
                )
        elif isinstance(node, ast.ClassDef):
            info.bindings.add(node.name)
            ci = ClassInfo(
                name=node.name,
                bases=tuple(decorator_name(b) for b in node.bases),
            )
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = sig_of(
                        sub, is_method=True, sig_preserving=sig_preserving
                    )
                    ci.method_nodes[sub.name] = sub
            info.classes[node.name] = ci
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    info.bindings.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            info.bindings.add(e.id)
            jit = _jit_call_info(node.value)
            if jit is not None and isinstance(node.targets[0], ast.Name):
                static, donated, impl = jit
                name = node.targets[0].id
                info.jit_entries[name] = JitEntry(
                    name=name,
                    modname=modname,
                    impl=impl,
                    static_argnames=static,
                    donate_argnames=donated,
                )
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            info.bindings.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            _collect_imports(info, node, top_level=True)
        elif isinstance(node, (ast.If, ast.Try)):
            # Conditional top-level defs (TYPE_CHECKING, fallbacks):
            # bind anything defined in any branch.
            for sub in ast.walk(node):
                if isinstance(
                    sub,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    info.bindings.add(sub.name)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            info.bindings.add(t.id)
                elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        if alias.name != "*":
                            info.bindings.add(
                                alias.asname or alias.name.split(".")[0]
                            )
    # Function-local imports matter for cross-module resolution too
    # (mid-function imports are idiomatic for lazy jax loading).
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            _collect_imports(info, node, top_level=False)
    return info


def _collect_imports(
    info: ModuleInfo, node: ast.Import | ast.ImportFrom, top_level: bool
) -> None:
    if isinstance(node, ast.Import):
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            if top_level:
                info.bindings.add(local)
            if alias.asname or "." not in alias.name:
                info.mod_imports.setdefault(local, alias.name)
    else:
        target = resolve_import_from(info, node)
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            if top_level:
                info.bindings.add(local)
            if target:
                info.from_imports.setdefault(local, (target, alias.name))


def resolve_import_from(info: ModuleInfo, node: ast.ImportFrom) -> str:
    """Absolute module a ``from X import ...`` pulls from ("" if the
    relative import escapes the indexed tree)."""
    if not node.level:
        return node.module or ""
    # Level 1 means "this package": for a package __init__ that is the
    # module itself; for a plain module it is the parent.
    drop = node.level - (1 if info.path.name == "__init__.py" else 0)
    if drop == 0:
        base = info.modname
    else:
        parts = info.modname.rsplit(".", drop)
        if len(parts) <= drop:
            return ""
        base = parts[0]
    return f"{base}.{node.module}" if node.module else base


def modname_for(path: Path, repo: Path) -> str:
    rel = path.relative_to(repo).with_suffix("")
    parts = list(rel.parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def build_index(
    files: list[Path], repo: Path, sig_preserving: set[str]
) -> dict[str, ModuleInfo]:
    index: dict[str, ModuleInfo] = {}
    for f in files:
        modname = modname_for(f, repo)
        index[modname] = collect_module(f, modname, sig_preserving)
    return index
