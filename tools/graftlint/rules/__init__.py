"""Rule modules. Importing this package registers every rule; the
registry is the single source of truth for ``--list-rules``, selection,
and the self-test harness."""

from __future__ import annotations

from tools.graftlint.core import Context, Rule, register

from tools.graftlint.rules import (  # noqa: E402,F401
    atomic,
    commit,
    configcheck,
    donate,
    lifecycle,
    locking,
    refcount,
    retrace,
    sync,
    trace,
    typecheck,
)


@register
class SuppressRule(Rule):
    """Suppression hygiene. The findings are produced by the driver
    (which owns suppression parsing); registering the id here gives it
    a catalog entry, ``--rule`` selectability, and a self-test fixture
    like every other rule."""

    id = "GL-SUPPRESS"
    title = "suppressions must carry a reason and name real rules"
    rationale = (
        "A reasonless disable is an unreviewable mute; a typo'd rule id "
        "is a silently disarmed check. Both are findings, and a "
        "reasonless disable does not suppress anything."
    )
    fixtures = {
        "pkg/bad_suppress.py": (
            "import os  # graftlint: disable=GL-SYNC\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        # Driver-implemented (needs the post-rule findings list).
        return None
