"""GL-ATOMIC — file writes inside the package must route through a
sanctioned atomic/durable write implementation.

The torn-state class PR 10 closed: a plain ``path.write_text`` /
``open(path, "w")`` is not atomic — a crash mid-write leaves a half
file that a reader (a resume, a Prometheus scraper, a session load)
then parses. The repo has exactly three sanctioned write disciplines,
each crash-safe by construction:

- ``obs.atomic_write_text`` — pid-suffixed tmp + ``os.replace``;
- the round journal's fsync append (``RoundJournal._write``) — the
  append-only WAL whose one crash artifact (a torn tail) the tolerant
  reader discards;
- ``DiskStore.put`` — tmp + replace with a content hash the reader
  verifies.

Any other write-mode ``open()`` / ``write_text`` / ``write_bytes``
under the configured package is a finding unless its enclosing
function is listed in ``atomic_funcs`` (the sanctioned implementations
themselves) or carries a reasoned inline suppression. Scope is the
package only: tools and tests write scratch files freely.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import function_table


def _write_mode(call: ast.Call) -> str:
    """The write-mode string of an ``open()`` call ("" for reads or
    non-constant modes)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and any(c in mode.value for c in "wax+")
    ):
        return mode.value
    return ""


@register
class AtomicRule(Rule):
    id = "GL-ATOMIC"
    title = "package file writes must use a sanctioned atomic discipline"
    rationale = (
        "A non-atomic write is a crash-shaped data corruption: the "
        "process dies mid-write and the next reader parses half a "
        "file. Every sanctioned implementation (tmp+replace, fsync'd "
        "append) already exists — new write sites must reuse one, not "
        "reinvent a torn-state bug."
    )
    fixtures = {
        "pkg/saver.py": (
            "import json\n"
            "\n"
            "def save_settings(path, settings):\n"
            "    path.write_text(json.dumps(settings))\n"
        ),
    }
    fixture_config = {"package": "pkg", "atomic_funcs": []}

    def check(self, ctx: Context) -> None:
        package = ctx.cfg.package
        allowed = set(ctx.cfg.atomic_funcs)
        funcs = function_table(ctx.index)
        # Call line -> enclosing function qualname, for the allowlist.
        for info in ctx.index.values():
            if not (
                info.modname == package
                or info.modname.startswith(package + ".")
            ):
                continue
            owners: dict[int, str] = {}
            for (mod, fkey), fe in funcs.items():
                if mod != info.modname:
                    continue
                for sub in ast.walk(fe.node):
                    if isinstance(sub, ast.Call):
                        owners[id(sub)] = fe.qualname
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                what = ""
                f = node.func
                if isinstance(f, ast.Name) and f.id == "open":
                    mode = _write_mode(node)
                    if mode:
                        what = f"open(..., {mode!r})"
                elif isinstance(f, ast.Attribute) and f.attr in (
                    "write_text",
                    "write_bytes",
                ):
                    what = f".{f.attr}()"
                if not what:
                    continue
                owner = owners.get(id(node), "")
                if owner in allowed:
                    continue
                ctx.report(
                    "GL-ATOMIC",
                    info.path,
                    node.lineno,
                    f"{what} in {owner or info.modname} writes a file "
                    "outside the sanctioned atomic disciplines — a "
                    "crash mid-write leaves a torn file; route through "
                    "obs.atomic_write_text / the journal's fsync append "
                    "/ DiskStore.put, or suppress with a reason the "
                    "write cannot tear",
                )
