"""GL-COMMIT — fresh device state bound to persistent engine attributes
must be committed to the mesh sharding at creation.

The double-compile class this repo has paid for twice: a freshly
created device array (``jnp.zeros``, ``init_cache``) carries
UnspecifiedValue sharding, while the same attribute after one step is a
mesh-committed program output — two jit signatures for one program, and
XLA silently compiles it twice (PR 5's admission cache, then the
identical bug again in PR 6's batcher row state). The fix is mechanical
— route the creation through ``_commit`` / ``jax.device_put`` — so the
check should be too.

At every assignment ``self.<attr> = <expr>`` (``attr`` in
``commit_attrs``) inside a ``commit_classes`` class, and at every
keyword ``<attr>=<expr>`` of a ``commit_holders`` constructor call
(``_Admission(cache=...)``), the value's ROOT must not be a bare
creator call (``commit_creators``): it must be wrapped in a committing
call (``commit_wrappers``), or be derived state (``.at[].set()``,
``jnp.zeros_like`` — sharding propagates from the existing operand).
Local flow is tracked: ``cache = init_cache(...)`` that later reaches
``_Admission(cache=cache)`` is reported at the sink.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.index import dotted_name


def _creator_name(expr: ast.expr, creators: set[str]) -> str:
    """The matching creator name when ``expr`` is a bare creation call
    ("" otherwise)."""
    if not isinstance(expr, ast.Call):
        return ""
    name = dotted_name(expr.func)
    if name in creators:
        return name
    tail = name.rsplit(".", 1)[-1] if name else ""
    if tail in creators:
        return tail
    return ""


def _is_wrapper(expr: ast.expr, wrappers: set[str]) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    name = dotted_name(expr.func)
    return bool(name) and name.rsplit(".", 1)[-1] in wrappers


@register
class CommitRule(Rule):
    id = "GL-COMMIT"
    title = "persistent device attrs committed to mesh sharding at creation"
    rationale = (
        "An uncommitted fresh array and a mesh-committed step output "
        "present two jit signatures for the same program: XLA compiles "
        "it twice, once per admission — compile time on the serving "
        "path, invisible until the retrace watch catches it on real "
        "hardware (the PR 5 admission-cache and PR 6 row-state bugs)."
    )
    fixtures = {
        "pkg/batcher.py": (
            "import jax.numpy as jnp\n"
            "\n"
            "class ContinuousBatcher:\n"
            "    def __init__(self, B):\n"
            "        self.active = jnp.zeros((B,), bool)\n"
            "        self.out_buf = self._commit(jnp.zeros((B, 4)))\n"
            "\n"
            "    def _commit(self, x):\n"
            "        return x\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        cfg = ctx.cfg
        classes = set(cfg.commit_classes)
        attrs = set(cfg.commit_attrs)
        creators = set(cfg.commit_creators)
        wrappers = set(cfg.commit_wrappers)
        holders = set(cfg.commit_holders)
        for info in ctx.index.values():
            if not any(c in info.classes for c in classes):
                continue
            for cname in classes & set(info.classes):
                for mname, mnode in info.classes[
                    cname
                ].method_nodes.items():
                    self._check_function(
                        ctx,
                        info,
                        f"{cname}.{mname}",
                        mnode,
                        attrs,
                        creators,
                        wrappers,
                        holders,
                        check_self=True,
                    )
            for fname, fnode in info.func_nodes.items():
                self._check_function(
                    ctx,
                    info,
                    fname,
                    fnode,
                    attrs,
                    creators,
                    wrappers,
                    holders,
                    check_self=False,
                )

    def _status(self, expr: ast.expr, env: dict, creators, wrappers) -> str:
        """"uncommitted" | "committed" | "other" for a value's root."""
        if isinstance(expr, ast.Name):
            return env.get(expr.id, "other")
        if isinstance(expr, ast.IfExp):
            for branch in (expr.body, expr.orelse):
                if (
                    self._status(branch, env, creators, wrappers)
                    == "uncommitted"
                ):
                    return "uncommitted"
            return "other"
        if _is_wrapper(expr, wrappers):
            return "committed"
        if _creator_name(expr, creators):
            return "uncommitted"
        return "other"

    def _check_function(
        self,
        ctx,
        info,
        where,
        fn,
        attrs,
        creators,
        wrappers,
        holders,
        *,
        check_self,
    ) -> None:
        def warn(node: ast.AST, sink: str) -> None:
            ctx.report(
                "GL-COMMIT",
                info.path,
                node.lineno,
                f"fresh device state reaches persistent {sink} in "
                f"{where} without flowing through a committing wrapper "
                f"({', '.join(sorted(wrappers))}) — an uncommitted "
                "creation and a mesh-committed step output are two jit "
                "signatures for one program (double compile); wrap the "
                "creation or suppress with a reason",
            )

        def check_holder_calls(expr: ast.expr, env: dict) -> None:
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if fname and fname.rsplit(".", 1)[-1] in holders:
                    for kw in node.keywords:
                        if kw.arg in attrs and (
                            self._status(
                                kw.value, env, creators, wrappers
                            )
                            == "uncommitted"
                        ):
                            warn(
                                kw.value,
                                f"{fname}({kw.arg}=...) holder field",
                            )

        def process_block(block: list, env: dict) -> None:
            # Statement-ordered, so each sink sees the bindings AS OF
            # its program point: a later rebind of a local must neither
            # poison an earlier (committed) holder use nor launder an
            # earlier uncommitted one.
            for stmt in block:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    process_block(stmt.body, {})  # own scope
                    continue
                # Expressions evaluated by THIS statement, before its
                # own binding takes effect.
                for field_val in ast.iter_fields(stmt):
                    _, value = field_val
                    if isinstance(value, ast.expr):
                        check_holder_calls(value, env)
                    elif isinstance(value, list) and value and isinstance(
                        value[0], ast.expr
                    ):
                        for v in value:
                            check_holder_calls(v, env)
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    t = stmt.targets[0]
                    status = self._status(
                        stmt.value, env, creators, wrappers
                    )
                    if isinstance(t, ast.Name):
                        env[t.id] = status
                    elif (
                        check_self
                        and isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr in attrs
                        and status == "uncommitted"
                    ):
                        warn(stmt, f"attribute self.{t.attr}")
                elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    if stmt.value is not None:
                        env[stmt.target.id] = self._status(
                            stmt.value, env, creators, wrappers
                        )
                # Child blocks in order (branch bindings merge
                # last-wins — fine: the rule is per-program-point
                # best-effort, and branches that disagree about
                # committedness are exactly the code GL-COMMIT exists
                # to make suspicious).
                for name_ in ("body", "orelse", "finalbody"):
                    child = getattr(stmt, name_, None)
                    if (
                        isinstance(child, list)
                        and child
                        and isinstance(child[0], ast.stmt)
                    ):
                        process_block(child, env)
                for handler in getattr(stmt, "handlers", []):
                    process_block(handler.body, env)

        process_block(fn.body, {})
