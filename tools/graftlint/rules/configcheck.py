"""GL-CONFIG — stale ``[tool.graftlint]`` allowlist / device-name
entries are themselves findings.

The inline-suppression machinery already refuses to let a mute outlive
its finding (GL-SUPPRESS's stale check); this rule gives the pyproject
table the same treatment. An allowlist entry that matches nothing in
the indexed package — a sync-allowlisted method that was renamed, a
device attribute that no longer exists, a refcount module that moved —
is a silently disarmed (or silently meaningless) piece of config: the
check it configured either stopped protecting anything or never will
again. Allowlists must not rot as code moves.

Runs only on FULL lints (default roots): on a ``--changed`` subset the
package is deliberately not all indexed, and "matches nothing in the
subset" proves nothing.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import function_table


def _pyproject_line(repo, needle: str) -> int:
    """Best-effort line of a config entry inside [tool.graftlint]."""
    path = repo / "pyproject.toml"
    if not path.exists():
        return 1
    in_table = False
    for i, line in enumerate(
        path.read_text(encoding="utf-8").splitlines(), start=1
    ):
        stripped = line.strip()
        if stripped.startswith("["):
            in_table = stripped == "[tool.graftlint]"
            continue
        if in_table and f'"{needle}"' in line or (
            in_table and stripped.startswith(needle)
        ):
            return i
    return 1


@register
class ConfigRule(Rule):
    id = "GL-CONFIG"
    title = "graftlint config entries must match indexed code"
    rationale = (
        "A sync-allowlist entry naming a renamed method, a device-name "
        "taint seed for a deleted local, or a refcount module that "
        "moved is config rot: the rule it configured silently stopped "
        "meaning anything. Stale inline suppressions are findings; "
        "stale table entries are too."
    )
    fixtures = {
        "pkg/sched.py": (
            "class Batcher:\n"
            "    def _advance(self):\n"
            "        return self.active\n"
        ),
    }
    fixture_config = {
        "package": "pkg",
        "sync_class": "Batcher",
        "sync_allowlist": ["_ghost_method"],
        "sync_device_attrs": ["active"],
        "sync_device_names": [],
        "refcount_modules": [],
        "refcount_pairs": [],
        "retrace_bucketers": [],
        "commit_classes": [],
        "commit_attrs": [],
        "commit_holders": [],
        "atomic_funcs": [],
        "lifecycle_class": "Batcher",
        "lifecycle_exits": [],
        "lifecycle_owned_attrs": [],
        "lifecycle_mutators": [],
        "fleet_lifecycle_class": "",  # fixture has no fleet machine
        "serve_lifecycle_class": "",  # fixture has no serve machine
        "weightres_lifecycle_class": "",  # nor a weight-ledger machine
        "autoscale_lifecycle_class": "",  # nor an autoscaler machine
        "lock_guards": [],  # fixture declares no locks
        "lock_thread_entries": [],
        "lock_blocking_calls": [],
    }

    def check(self, ctx: Context) -> None:
        if not ctx.full_run:
            return
        cfg = ctx.cfg
        if cfg.package not in ctx.index:
            return  # package not (fully) indexed: staleness unprovable

        # -- what the indexed package actually contains ---------------
        class_defs: dict[str, list] = {}
        method_names: set[str] = set()
        funcs = function_table(ctx.index)
        for info in ctx.index.values():
            for cname, ci in info.classes.items():
                class_defs.setdefault(cname, []).append(ci)
                method_names.update(ci.method_nodes)

        def class_body_names(cname: str) -> tuple[set[str], set[str]]:
            """(attribute names, bare names) appearing in the class."""
            attrs: set[str] = set()
            names: set[str] = set()
            for ci in class_defs.get(cname, []):
                for node in ci.method_nodes.values():
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute):
                            attrs.add(sub.attr)
                        elif isinstance(sub, ast.Name):
                            names.add(sub.id)
            return attrs, names

        def methods_of(cname: str) -> set[str]:
            out: set[str] = set()
            for ci in class_defs.get(cname, []):
                out.update(ci.method_nodes)
            return out

        stale: list[tuple[str, str]] = []  # (knob, entry)

        def need(ok: bool, knob: str, entry: str) -> None:
            if not ok:
                stale.append((knob, entry))

        need(cfg.sync_class in class_defs, "sync_class", cfg.sync_class)
        sync_methods = methods_of(cfg.sync_class)
        sync_attrs, sync_names = class_body_names(cfg.sync_class)
        for m in cfg.sync_allowlist:
            need(m in sync_methods, "sync_allowlist", m)
        for a in cfg.sync_device_attrs:
            need(a in sync_attrs, "sync_device_attrs", a)
        for n in cfg.sync_device_names:
            need(n in sync_names, "sync_device_names", n)
        for mod in cfg.refcount_modules:
            need(mod in ctx.index, "refcount_modules", mod)
        for pair in cfg.refcount_pairs:
            for name in pair.split("="):
                need(
                    name.strip() in method_names,
                    "refcount_pairs",
                    name.strip(),
                )
        all_funcs = {fe.name for fe in funcs.values()}
        for b in cfg.retrace_bucketers:
            need(b in all_funcs, "retrace_bucketers", b)
        for c in cfg.commit_classes:
            need(c in class_defs, "commit_classes", c)
        for h in cfg.commit_holders:
            need(h in class_defs or h in all_funcs, "commit_holders", h)
        commit_scope_attrs: set[str] = set()
        for c in cfg.commit_classes:
            commit_scope_attrs |= class_body_names(c)[0]
        for h in cfg.commit_holders:
            for ci in class_defs.get(h, []):
                commit_scope_attrs.update(ci.methods)
        # Holder keyword fields: dataclass field names are module-level
        # AnnAssign targets inside the class body — approximate with
        # "attribute or method or field name used anywhere in a commit
        # class / holder".
        for info in ctx.index.values():
            for cname in set(cfg.commit_holders) & set(info.classes):
                for node in ast.walk(info.tree):
                    if (
                        isinstance(node, ast.ClassDef)
                        and node.name == cname
                    ):
                        for sub in node.body:
                            if isinstance(
                                sub, ast.AnnAssign
                            ) and isinstance(sub.target, ast.Name):
                                commit_scope_attrs.add(sub.target.id)
        for a in cfg.commit_attrs:
            need(a in commit_scope_attrs, "commit_attrs", a)
        qualnames = {fe.qualname for fe in funcs.values()}
        for q in cfg.atomic_funcs:
            need(q in qualnames, "atomic_funcs", q)
        # Every configured lifecycle machine (the batcher's slot
        # machine, the fleet router's replica machine, the serve
        # scheduler's request machine) validates the same way; the
        # knob-name prefix distinguishes findings.
        for prefix, (
            cls_name,
            release,
            exits,
            owned,
            mutators,
        ) in cfg.named_lifecycle_machines():
            need(cls_name in class_defs, f"{prefix}_class", cls_name)
            lc_methods = methods_of(cls_name)
            lc_attrs, _ = class_body_names(cls_name)
            need(release in lc_methods, f"{prefix}_release", release)
            for m in exits:
                need(m in lc_methods, f"{prefix}_exits", m)
            for m in mutators:
                need(m in lc_methods, f"{prefix}_mutators", m)
            for a in owned:
                need(a in lc_attrs, f"{prefix}_owned_attrs", a)

        # -- lock-guard table (GL-LOCK's configuration) ----------------
        self._check_lock_table(ctx, class_defs, need)

        for knob, entry in stale:
            ctx.report(
                "GL-CONFIG",
                ctx.repo / "pyproject.toml",
                _pyproject_line(ctx.repo, entry),
                f"[tool.graftlint] {knob} entry {entry!r} matches "
                "nothing in the indexed package — the code moved or "
                "was renamed; update or delete the entry (stale "
                "allowlists silently disarm their rule)",
            )

    # -- GL-LOCK config ---------------------------------------------------

    _LOCK_CTORS = frozenset(
        {"Lock", "RLock", "Condition", "make_lock", "make_rlock"}
    )

    def _check_lock_table(self, ctx: Context, class_defs, need) -> None:
        """The ``lock_guards`` table is GL-LOCK's ground truth, so it
        rots two ways: an entry can name code that moved (stale — same
        failure mode as every allowlist), and code can grow a lock the
        table never heard of (a silently unguarded lock, which is
        worse). Both directions are findings."""
        cfg = ctx.cfg
        try:
            guards = cfg.parsed_lock_guards()
        except ValueError as exc:
            ctx.report(
                "GL-CONFIG",
                ctx.repo / "pyproject.toml",
                _pyproject_line(ctx.repo, "lock_guards"),
                f"[tool.graftlint] {exc}",
            )
            guards = []
        try:
            entries = cfg.parsed_thread_entries()
        except ValueError as exc:
            ctx.report(
                "GL-CONFIG",
                ctx.repo / "pyproject.toml",
                _pyproject_line(ctx.repo, "lock_thread_entries"),
                f"[tool.graftlint] {exc}",
            )
            entries = []

        def class_decls(modname: str, cname: str) -> tuple[set[str], set]:
            """(attr names assigned/used in the class, incl. dataclass
            field AnnAssign targets in the class body)."""
            info = ctx.index.get(modname)
            attrs: set[str] = set()
            if info is None:
                return attrs, set()
            for node in ast.walk(info.tree):
                if isinstance(node, ast.ClassDef) and node.name == cname:
                    for sub in node.body:
                        if isinstance(sub, ast.AnnAssign) and isinstance(
                            sub.target, ast.Name
                        ):
                            attrs.add(sub.target.id)
                        elif isinstance(sub, ast.Assign):
                            for t in sub.targets:
                                if isinstance(t, ast.Name):
                                    attrs.add(t.id)
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute):
                            attrs.add(sub.attr)
            return attrs, set()

        def module_names(modname: str) -> set[str]:
            info = ctx.index.get(modname)
            if info is None:
                return set()
            return {
                n.id for n in ast.walk(info.tree) if isinstance(n, ast.Name)
            }

        for g in guards:
            label = f"{g.module}:{g.name}"
            if g.module not in ctx.index:
                need(False, "lock_guards", label)
                continue
            if g.classname:
                info = ctx.index[g.module]
                if g.classname not in info.classes:
                    need(False, "lock_guards", label)
                    continue
                attrs, _ = class_decls(g.module, g.classname)
                for alias in g.aliases:
                    need(alias in attrs, "lock_guards", f"{label}|{alias}")
                for a in g.guarded:
                    need(a in attrs, "lock_guards", f"{label}={a}")
            else:
                names = module_names(g.module)
                for alias in g.aliases:
                    need(alias in names, "lock_guards", f"{label}|{alias}")
                for a in g.guarded:
                    need(a in names, "lock_guards", f"{label}={a}")

        for module, classname, func in entries:
            label = f"{module}:{classname + '.' if classname else ''}{func}"
            info = ctx.index.get(module)
            if info is None:
                need(False, "lock_thread_entries", label)
                continue
            if classname:
                ci = info.classes.get(classname)
                need(
                    ci is not None and func in ci.method_nodes,
                    "lock_thread_entries",
                    label,
                )
            else:
                need(func in info.func_nodes, "lock_thread_entries", label)

        # -- unlisted locks: every Lock/RLock/Condition constructed in
        # the package must appear in the guards table (possibly with an
        # empty guarded set — "no guarded state" is a reviewed claim,
        # absence is not).
        listed: dict[tuple[str, str], set[str]] = {}
        for g in guards:
            listed.setdefault((g.module, g.classname), set()).update(
                g.aliases
            )

        def is_lock_ctor(value: ast.expr) -> bool:
            if not isinstance(value, ast.Call):
                return False
            f = value.func
            name = (
                f.attr
                if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else ""
            )
            return name in self._LOCK_CTORS

        pkg = cfg.package
        for modname, info in ctx.index.items():
            if modname != pkg and not modname.startswith(pkg + "."):
                continue
            if modname.rsplit(".", 1)[-1] == "lockdep":
                continue  # the sanitizer's own internals
            # Module-level lock bindings.
            for node in info.tree.body:
                if isinstance(node, ast.Assign) and is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in (
                            listed.get((modname, ""), set())
                        ):
                            ctx.report(
                                "GL-CONFIG",
                                info.path,
                                node.lineno,
                                f"lock {t.id!r} in {modname} is not "
                                "listed in [tool.graftlint] lock_guards "
                                "— every lock must declare its guarded "
                                "state (an empty guarded set is a "
                                "reviewed claim; absence is an "
                                "unreviewed lock)",
                            )
            # self.<attr> lock bindings inside class methods.
            for cname, ci in info.classes.items():
                allowed = listed.get((modname, cname), set())
                for mnode in ci.method_nodes.values():
                    for sub in ast.walk(mnode):
                        if not (
                            isinstance(sub, ast.Assign)
                            and is_lock_ctor(sub.value)
                        ):
                            continue
                        for t in sub.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and t.attr not in allowed
                            ):
                                ctx.report(
                                    "GL-CONFIG",
                                    info.path,
                                    sub.lineno,
                                    f"lock {cname}.{t.attr} in "
                                    f"{modname} is not listed in "
                                    "[tool.graftlint] lock_guards — "
                                    "every lock must declare its "
                                    "guarded state (an empty guarded "
                                    "set is a reviewed claim; absence "
                                    "is an unreviewed lock)",
                                )
