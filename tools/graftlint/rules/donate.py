"""GL-DONATE — no raw reference to a donated buffer may outlive its
dispatch.

``donate_argnames`` hands a buffer's device memory to the compiled
program: after the dispatch the Python object still exists, but its
buffer is deleted. The idiomatic drive loop rebinds the name from the
program's output (``pool, out_buf = step(pool, out_buf)``) — safe. The
bug class (PR 9's streaming entry) is storing a RAW ALIAS of the buffer
somewhere that survives into the next dispatch: the stored tuple
element points at memory the next donation deletes, and the depth-bound
fetch one iteration later reads garbage (or crashes) only under
pipelining on real hardware. The committed fix was a ``jnp.copy``
snapshot; this rule makes the snapshot mandatory.

At every statically resolvable call to a jit entry with
``donate_argnames`` (discovered in the index pass, same resolution as
GL-RETRACE), the attribute/name bound to each donated parameter is
collected — including transitively: a method that donates ``self.X``
marks its own call sites as donating ``self.X`` (bounded by
``dataflow_depth``). A read of a donated value in an ESCAPE position —
element of a tuple/list/set/dict literal, argument to
``.append``/``.add``/…, a ``return``/``yield`` — is a finding when a
donating dispatch can execute after it (it shares a loop with one, or
one follows it in the function), unless the read is wrapped in a
snapshot call (``donate_snapshots``: ``jnp.copy`` & friends).
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import FuncEntry, bind_args, function_table
from tools.graftlint.index import ModuleInfo, dotted_name

_STORE_METHODS = {
    "append",
    "appendleft",
    "add",
    "extend",
    "insert",
    "put",
    "put_nowait",
}


def _donating_entries(
    ctx: Context, funcs: dict
) -> dict[str, tuple]:
    """dotted name -> (entry, impl FuncEntry) for jit entries that
    donate; argument binding reuses the shared dataflow machinery on
    the impl's definition."""
    out: dict[str, tuple] = {}
    for modname, info in ctx.index.items():
        for entry in info.jit_entries.values():
            if not entry.donate_argnames:
                continue
            impl = funcs.get((modname, entry.impl))
            if impl is not None:
                out[f"{modname}.{entry.name}"] = (entry, impl)
    return out


def _resolve_entry(info: ModuleInfo, func: ast.expr, table: dict):
    """Like dataflow.resolve_call, but against the jit-entry table:
    assignment-bound entries (``step = partial(jax.jit, …)(impl)``) are
    not function defs, so the shared resolver — which requires an
    indexed body — deliberately cannot name them."""
    if isinstance(func, ast.Name):
        name = func.id
        hit = table.get(f"{info.modname}.{name}")
        if hit:
            return hit
        if name in info.from_imports:
            src_mod, orig = info.from_imports[name]
            return table.get(f"{src_mod}.{orig}")
    elif isinstance(func, ast.Attribute) and isinstance(
        func.value, ast.Name
    ):
        target = info.mod_imports.get(func.value.id)
        if target is not None:
            return table.get(f"{target}.{func.attr}")
    return None


def _donated_keys(
    call: ast.Call, entry, impl: FuncEntry
) -> list[tuple[str, str]]:
    """Donated-value keys bound at this call site: ("attr", X) for
    ``self.X`` arguments, ("name", x) for bare locals."""
    donated = set(entry.donate_argnames)
    keys: list[tuple[str, str]] = []
    for param, arg in bind_args(impl, call):
        if param not in donated:
            continue
        if (
            isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"
        ):
            keys.append(("attr", arg.attr))
        elif isinstance(arg, ast.Name):
            keys.append(("name", arg.id))
    return keys


def _match_key(node: ast.expr, key: tuple[str, str]) -> bool:
    kind, name = key
    if not isinstance(
        getattr(node, "ctx", ast.Load()), ast.Load
    ):
        return False  # a rebind target is the idiom, not an alias
    if kind == "attr":
        return (
            isinstance(node, ast.Attribute)
            and node.attr == name
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        )
    return isinstance(node, ast.Name) and node.id == name


@register
class DonateRule(Rule):
    id = "GL-DONATE"
    title = "donated buffers must be snapshotted before any stored alias"
    rationale = (
        "A donated buffer's memory is deleted at dispatch; a raw alias "
        "stored for a later fetch reads freed memory — but only under "
        "pipelining on real hardware, which is why the class ships: "
        "CPU tests pass, the TPU run corrupts. jnp.copy is a cheap "
        "device-side op that overlaps compute; make it mandatory."
    )
    fixtures = {
        "pkg/drive.py": (
            "from functools import partial\n"
            "import jax\n"
            "\n"
            "def _impl(pool, out_buf):\n"
            "    return pool, out_buf\n"
            "\n"
            "step = partial(jax.jit, donate_argnames=('pool', 'out_buf'))"
            "(_impl)\n"
            "\n"
            "def drive(pool, out_buf, n):\n"
            "    entries = []\n"
            "    for _ in range(n):\n"
            "        entries.append((out_buf,))\n"
            "        pool, out_buf = step(pool, out_buf)\n"
            "    return entries\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        funcs = function_table(ctx.index)
        table = _donating_entries(ctx, funcs)
        if not table:
            return
        snapshots = set(ctx.cfg.donate_snapshots)

        # Pass 1: direct donating call sites per function, and per-
        # method donated-self-attr summaries.
        sites: dict[tuple[str, str], list[tuple[ast.Call, tuple, list]]] = {}
        summaries: dict[tuple[str, str], set[str]] = {}
        for fkey, fe in funcs.items():
            info = ctx.index[fe.modname]
            for node in ast.walk(fe.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = _resolve_entry(info, node.func, table)
                if hit is None:
                    continue
                entry, impl = hit
                keys = _donated_keys(node, entry, impl)
                if keys:
                    sites.setdefault(fkey, []).append(
                        (node, entry, keys)
                    )
                    if fe.classname:
                        summaries.setdefault(fkey, set()).update(
                            n for k, n in keys if k == "attr"
                        )

        # Pass 2 (bounded): a call to a method that donates self.X is a
        # donating site for self.X at the caller.
        for _ in range(max(1, ctx.cfg.dataflow_depth)):
            changed = False
            for fkey, fe in funcs.items():
                if not fe.classname:
                    continue
                info = ctx.index[fe.modname]
                for node in ast.walk(fe.node):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                    ):
                        continue
                    callee = (
                        fe.modname,
                        f"{fe.classname}.{node.func.attr}",
                    )
                    attrs = summaries.get(callee)
                    if not attrs:
                        continue
                    keys = [("attr", a) for a in sorted(attrs)]
                    existing = sites.setdefault(fkey, [])
                    if not any(n is node for n, _, _ in existing):
                        existing.append((node, None, keys))
                        changed = True
                    have = summaries.setdefault(fkey, set())
                    if attrs - have:
                        have.update(attrs)
                        changed = True
            if not changed:
                break

        for fkey, fsites in sites.items():
            self._check_escapes(
                ctx, funcs[fkey], fsites, snapshots
            )

    def _check_escapes(self, ctx, fe, fsites, snapshots) -> None:
        info = ctx.index[fe.modname]
        # Parent map + loop ranges for the position rule.
        parents: dict[int, ast.AST] = {}
        loops: list[tuple[int, int]] = []
        for node in ast.walk(fe.node):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                loops.append(
                    (node.lineno, getattr(node, "end_lineno", node.lineno))
                )

        def may_dispatch_after(line: int, dispatch_lines: list[int]) -> bool:
            for d in dispatch_lines:
                if d >= line:
                    return True
                for lo, hi in loops:
                    if lo <= d <= hi and lo <= line <= hi:
                        return True
            return False

        by_key: dict[tuple[str, str], list] = {}
        for node, entry, keys in fsites:
            for key in keys:
                by_key.setdefault(key, []).append((node.lineno, entry))

        for key, dispatches in by_key.items():
            dispatch_lines = [ln for ln, _ in dispatches]
            entry_names = sorted(
                {e.name for _, e in dispatches if e is not None}
            ) or ["a donating dispatch"]
            label = (
                f"self.{key[1]}" if key[0] == "attr" else key[1]
            )
            for node in ast.walk(fe.node):
                if not isinstance(node, ast.expr) or not _match_key(
                    node, key
                ):
                    continue
                escape = self._escape_context(
                    node, parents, snapshots, fe.node
                )
                if escape is None:
                    continue
                if not may_dispatch_after(node.lineno, dispatch_lines):
                    continue
                where = (
                    f"{fe.classname}.{fe.name}"
                    if fe.classname
                    else fe.name
                )
                ctx.report(
                    "GL-DONATE",
                    info.path,
                    node.lineno,
                    f"{label} is donated to {', '.join(entry_names)} "
                    f"and a raw reference escapes into {escape} in "
                    f"{where} — the buffer is deleted at the next "
                    "dispatch; snapshot it first (jnp.copy) or suppress "
                    "with a reason naming why no dispatch can follow",
                )

    def _escape_context(
        self,
        node: ast.expr,
        parents: dict,
        snapshots: set,
        fn_node: ast.AST,
    ) -> str | None:
        """The escape kind for a donated-value read, or None when the
        read is safe (call argument, rebind target, snapshotted)."""
        child = node
        while True:
            parent = parents.get(id(child))
            if parent is None or isinstance(parent, ast.stmt):
                if isinstance(parent, ast.Return):
                    return "a return value"
                if (
                    isinstance(parent, ast.Assign)
                    and child is parent.value
                    and any(
                        not isinstance(t, (ast.Name, ast.Tuple, ast.List))
                        for t in parent.targets
                    )
                ):
                    # self.other = self.out_buf — an attribute/subscript
                    # alias that survives the next dispatch.
                    return "an attribute store"
                return None
            if isinstance(parent, ast.Call):
                name = dotted_name(parent.func)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if tail in snapshots:
                    return None  # snapshotted — safe
                if (
                    tail in _STORE_METHODS
                    and child in parent.args
                ):
                    return f"a .{tail}() store"
                return None  # plain call argument: consumed, not stored
            if isinstance(
                parent, (ast.Tuple, ast.List, ast.Set, ast.Dict)
            ):
                if self._is_staged_args(parent, parents, fn_node):
                    return None
                return "a container literal"
            if isinstance(parent, (ast.Yield, ast.YieldFrom)):
                return "a yield"
            child = parent

    @staticmethod
    def _is_staged_args(
        container: ast.expr, parents: dict, fn_node: ast.AST
    ) -> bool:
        """The staged-args idiom: ``args = (…, buf, …)`` where EVERY
        later read of ``args`` is a ``*args`` splat into a call — the
        tuple is consumed by the dispatch itself and rebuilt before the
        next one, so it is not a surviving alias. (The PR 9 bug shape —
        ``entry = (…); inflight.append(entry)`` — has a non-splat read
        and still fires.)"""
        stmt = parents.get(id(container))
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.value is container
        ):
            return False
        name = stmt.targets[0].id
        uses = [
            n
            for n in ast.walk(fn_node)
            if isinstance(n, ast.Name)
            and n.id == name
            and isinstance(n.ctx, ast.Load)
        ]
        return bool(uses) and all(
            isinstance(parents.get(id(u)), ast.Starred) for u in uses
        )
