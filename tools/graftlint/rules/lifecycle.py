"""GL-LIFECYCLE — every slot-release path must go through the one
shared surgery, and slot ownership state is written nowhere else.

GL-REFCOUNT guards acquire/release PAIRS; this rule generalizes it to
lifecycle STATE MACHINES — the scheduler's slot machine
(``ContinuousBatcher._release_slot``) and the fleet router's replica
machine (``FleetRouter._retire_replica``), each configured as a
(class, release, exits, owned attrs, mutators) tuple via
``GraftlintConfig.lifecycle_machines()``. The batcher's release
surgery is deliberately the single implementation shared by finish /
evict / cancel / watchdog (the PR 6 lesson: two fault paths
hand-rolled the same surgery and drifted — one left ``_slot_seq``
stale); the router's retirement surgery is the same discipline for
replicas (transport death, heartbeat miss, and shutdown must all
funnel through one exit). Two invariants per machine, both
interprocedural:

1. **Exit reachability** — every configured slot-exit path
   (``lifecycle_exits``: the finish/evict/cancel/watchdog entry
   points) must reach ``lifecycle_release`` through the call graph
   within ``dataflow_depth`` hops. A new exit path that forgets the
   surgery is a finding at its ``def`` line.
2. **Surgery ownership** — the slot-ownership attributes
   (``lifecycle_owned_attrs``: ``_slot_req``, ``_slot_seq``, …) may be
   written only by the release surgery, ``__init__``, and the
   configured acquisition/mutator methods (``lifecycle_mutators``).
   A hand-rolled partial release anywhere else is exactly the drift
   the shared surgery exists to prevent.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import FuncEntry, function_table, reaches


def _target_attr(target: ast.expr) -> str:
    """The ``self.<attr>`` name a write targets, through subscripts:
    ``self._slot_req[slot] = ...`` and ``self._slot_gen[slot] += 1``
    both resolve to the attribute."""
    node = target
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


@register
class LifecycleRule(Rule):
    id = "GL-LIFECYCLE"
    title = "slot exits reach the shared release surgery; no side writes"
    rationale = (
        "Slot release has four callers (finish, evict, cancel, "
        "watchdog) and one correct implementation. A fifth path that "
        "hand-rolls the surgery — or forgets it — leaks pages, leaves "
        "stale ownership, or delivers a dead slot's tokens to its new "
        "owner; the drift is invisible until the state machines "
        "disagree under load."
    )
    fixtures = {
        "pkg/sched.py": (
            "class ContinuousBatcher:\n"
            "    def _release_slot(self, slot):\n"
            "        self._slot_req[slot] = None\n"
            "        self._slot_seq[slot] = None\n"
            "    def _finish_slot(self, slot):\n"
            "        self._release_slot(slot)\n"
            "    def _cancel_slot(self, slot):\n"
            "        # hand-rolled partial release: misses _slot_seq\n"
            "        self._slot_req[slot] = None\n"
        ),
    }
    fixture_config = {
        "lifecycle_class": "ContinuousBatcher",
        "lifecycle_release": "_release_slot",
        "lifecycle_exits": ["_finish_slot", "_cancel_slot"],
        "lifecycle_owned_attrs": ["_slot_req", "_slot_seq"],
        "lifecycle_mutators": [],
        "fleet_lifecycle_class": "",  # fixture has no fleet machine
        "serve_lifecycle_class": "",  # fixture has no serve machine
        "weightres_lifecycle_class": "",  # nor a weight-ledger machine
        "autoscale_lifecycle_class": "",  # nor an autoscaler machine
        "handoff_lifecycle_class": "",  # nor a handoff ledger
    }

    def check(self, ctx: Context) -> None:
        cfg = ctx.cfg
        table = function_table(ctx.index)  # shared across all machines
        for (
            cls_name,
            release,
            exits,
            owned_attrs,
            mutators,
        ) in cfg.lifecycle_machines():
            owned = set(owned_attrs)
            allowed_writers = set(mutators) | {release, "__init__"}
            for info in ctx.index.values():
                ci = info.classes.get(cls_name)
                if ci is None:
                    continue
                for exit_name in exits:
                    node = ci.method_nodes.get(exit_name)
                    if node is None:
                        continue  # GL-CONFIG flags the stale entry
                    entry = FuncEntry(
                        info.modname, cls_name, exit_name, node
                    )
                    if not reaches(
                        ctx.index,
                        entry,
                        release,
                        depth=cfg.dataflow_depth,
                        table=table,
                    ):
                        ctx.report(
                            "GL-LIFECYCLE",
                            info.path,
                            node.lineno,
                            f"lifecycle exit path {cls_name}."
                            f"{exit_name} never reaches the shared "
                            f"release surgery {release}() (within "
                            f"{cfg.dataflow_depth} call hops) — an "
                            "exit that skips the surgery leaks "
                            "resources or leaves stale ownership; "
                            f"route it through {release}() or "
                            "suppress with a reason",
                        )
                for mname, mnode in ci.method_nodes.items():
                    if mname in allowed_writers:
                        continue
                    for sub in ast.walk(mnode):
                        targets: list[ast.expr] = []
                        if isinstance(sub, ast.Assign):
                            targets = list(sub.targets)
                        elif isinstance(
                            sub, (ast.AugAssign, ast.AnnAssign)
                        ):
                            targets = [sub.target]
                        for t in targets:
                            attr = _target_attr(t)
                            if attr in owned:
                                ctx.report(
                                    "GL-LIFECYCLE",
                                    info.path,
                                    sub.lineno,
                                    f"lifecycle-owned state self.{attr} "
                                    f"written in {cls_name}."
                                    f"{mname}, outside the shared "
                                    f"release surgery ({release}) and "
                                    "the sanctioned mutators "
                                    f"({', '.join(sorted(allowed_writers))})"
                                    " — hand-rolled lifecycle writes "
                                    "are exactly the drift the shared "
                                    "surgery prevents; move the write "
                                    "or suppress with a reason",
                                )
