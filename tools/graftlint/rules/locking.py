"""GL-LOCK — static lock discipline for the threaded serving stack.

Since PR 13 the daemon worker threads, the EnginePump, the autoscaler
tick thread, weight-residency prefetch threads, and fleet heartbeats
share ~15 locks with no machine-checked statement of which lock guards
which state or which acquisition orders are legal. Three rules pin it:

- **GL-LOCK-GUARD** — the ``[tool.graftlint] lock_guards`` table maps
  each declared lock to the attributes it guards; any read/write of a
  guarded attribute reachable from a thread entry point (discovered
  ``threading.Thread`` targets and ``Thread``-subclass ``run`` methods
  plus the configured ``lock_thread_entries``) that is not dominated
  by a ``with <lock>`` on the owning lock is a finding. Deliberate
  lock-free fast paths carry the same reasoned inline disables GL-SYNC
  uses.
- **GL-LOCK-ORDER** — the static acquisition-order graph: a nested
  ``with`` adds an edge, and a call made while holding L1 that can
  reach an acquire of L2 adds L1→L2 through the call graph. Any cycle
  is a finding; the discovered order is emitted into ``--json``
  (``artifacts.lock_order``) so the runtime lockdep sanitizer
  (adversarial_spec_tpu/resilience/lockdep.py) and docs/locking.md
  share one canonical hierarchy.
- **GL-LOCK-BLOCKING** — calls that can block indefinitely or for
  device-scale time (``lock_blocking_calls``: sleeps, fsync,
  subprocess, device syncs, engine ``chat`` dispatch, ``wait`` on a
  *different* lock's condition) while any tracked lock is held. This
  pins the PR 15 review fix — the GB-scale demotion gather moved
  outside the engine lock — as a checked rule instead of folklore.

The analysis is deliberately conservative: ``with`` scopes are lexical,
held-on-entry sets for caller-holds helpers come from a fixed point
over *resolvable* call sites (``self.method``/name/module-attr calls;
cross-object attribute calls fall back to name matching for
reachability), and callbacks stored in attributes are invisible — the
runtime lockdep sanitizer is the dynamic complement that catches those.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import function_table, resolve_call
from tools.graftlint.index import dotted_name

# Constructor-family methods own their instance exclusively: guarded
# attribute writes there are initialization, not racing access.
_CTOR_NAMES = ("__init__", "__post_init__", "__new__")

# Names the call fallback must never match: they collide with builtin
# container/primitive methods (``self._roles.get(...)`` is a dict get,
# not DiskStore.get), so a name match is overwhelmingly a false edge.
_FALLBACK_STOPLIST = frozenset(
    {
        "get", "set", "add", "pop", "put", "items", "keys", "values",
        "update", "clear", "reset", "copy", "count", "index", "insert",
        "remove", "discard", "extend", "append", "appendleft", "popleft",
        "setdefault", "sort", "reverse", "join", "split", "strip",
        "startswith", "endswith", "encode", "decode", "format", "replace",
        "read", "write", "flush", "close", "open", "seek", "submit",
        "result", "cancel", "wait", "notify", "notify_all", "acquire",
        "release", "locked", "is_set", "start", "run", "group", "match",
        "search", "send", "recv", "empty", "full", "qsize", "lower",
        "upper", "total_seconds", "exists", "mkdir", "unlink",
    }
)


@dataclass
class _Acquire:
    guard: str  # canonical lock name acquired
    lineno: int
    held: frozenset  # lexically held just before this acquire


@dataclass
class _Access:
    guard: str  # lock that must be held
    attr: str
    lineno: int
    held: frozenset  # lexically held at the access


@dataclass
class _CallSite:
    dotted: str  # dotted text of the call target ("self._sleep")
    lineno: int
    held: frozenset  # lexically held at the call
    # Strict candidates (resolved, or a UNIQUE non-stoplisted name
    # match): feed the entry-held fixed point, the acquire closure,
    # and GL-LOCK-ORDER edges — a spurious edge there manufactures
    # cycles or dissolves a caller-holds helper's held set.
    callees: tuple = ()
    # Broad candidates (every non-stoplisted name match): feed only
    # GL-LOCK-GUARD's reachability BFS, where over-approximation just
    # means more functions get their (real) accesses checked.
    reach: tuple = ()
    resolved: bool = False  # True when callees came from resolve_call
    receiver_lock: str | None = None  # x.wait(): lock x aliases, if any
    thread_target: tuple | None = None  # threading.Thread(target=...)


@dataclass
class _FuncFacts:
    acquires: list = field(default_factory=list)
    accesses: list = field(default_factory=list)
    calls: list = field(default_factory=list)


class LockAnalysis:
    """Shared per-run substrate for the three GL-LOCK rules: lock/guard
    lookup tables, per-function with-scope facts, resolvable call
    edges, the entry-held fixed point, and thread-entry discovery."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.error: str | None = None
        try:
            self.guards = ctx.cfg.parsed_lock_guards()
            self.entries_cfg = ctx.cfg.parsed_thread_entries()
        except ValueError as e:
            self.error = str(e)
            self.guards = []
            self.entries_cfg = []
            self.facts = {}
            return
        pkg = ctx.cfg.package
        self.universe = frozenset(g.name for g in self.guards)
        # Lock-expression lookup: (module, class, attr) and (module,
        # global) exact matches, plus package-unique alias attributes
        # for cross-object expressions (``self._router._mlock``).
        self.class_alias: dict[tuple, object] = {}
        self.mod_alias: dict[tuple, object] = {}
        self.guarded_class: dict[tuple, object] = {}
        self.guarded_mod: dict[tuple, object] = {}
        alias_count: dict[str, list] = {}
        for g in self.guards:
            for a in g.aliases:
                alias_count.setdefault(a, []).append(g)
                if g.classname:
                    self.class_alias[(g.module, g.classname, a)] = g
                else:
                    self.mod_alias[(g.module, a)] = g
            for attr in g.guarded:
                if g.classname:
                    self.guarded_class[(g.module, g.classname, attr)] = g
                else:
                    self.guarded_mod[(g.module, attr)] = g
        self.attr_unique = {
            a: gs[0] for a, gs in alias_count.items() if len(gs) == 1
        }

        table = function_table(ctx.index)
        # The lockdep sanitizer itself manipulates raw primitives on
        # behalf of every tracked lock — analyzing it would attribute
        # every lock's behavior to its internals (self-observation).
        self.table = {
            k: fe
            for k, fe in table.items()
            if (fe.modname == pkg or fe.modname.startswith(pkg + "."))
            and fe.modname.rsplit(".", 1)[-1] != "lockdep"
        }
        # Name-based call fallback: cross-object attribute calls
        # (``sched.submit_units(...)``) are not statically resolvable;
        # matching the attribute name against package definitions keeps
        # the reachability closure honest at the cost of noise.
        self.by_name: dict[str, list] = {}
        self.by_name_reach: dict[str, list] = {}
        for k, fe in self.table.items():
            if fe.name.startswith("__"):
                continue
            self.by_name_reach.setdefault(fe.name, []).append(k)
            if fe.name not in _FALLBACK_STOPLIST:
                self.by_name.setdefault(fe.name, []).append(k)

        self.facts: dict[tuple, _FuncFacts] = {}
        for k, fe in self.table.items():
            self.facts[k] = self._scan_function(fe)
        self._resolve_callees()
        self.thread_roots = self._discover_roots()
        self.entry_held = self._entry_held_fixpoint()
        self.acq_closure = self._acquire_closure()

    # -- per-function with-scope scan ---------------------------------

    def _scan_function(self, fe) -> _FuncFacts:
        info = self.ctx.index[fe.modname]
        facts = _FuncFacts()

        def lock_of(expr) -> str | None:
            if isinstance(expr, ast.Attribute):
                if (
                    isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and fe.classname
                ):
                    g = self.class_alias.get(
                        (fe.modname, fe.classname, expr.attr)
                    )
                    if g is not None:
                        return g.name
                g = self.attr_unique.get(expr.attr)
                return g.name if g is not None else None
            if isinstance(expr, ast.Name):
                g = self.mod_alias.get((fe.modname, expr.id))
                return g.name if g is not None else None
            return None

        def access_of(node) -> str | None:
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and fe.classname
            ):
                g = self.guarded_class.get(
                    (fe.modname, fe.classname, node.attr)
                )
                return g.name if g is not None else None
            if isinstance(node, ast.Name):
                g = self.guarded_mod.get((fe.modname, node.id))
                return g.name if g is not None else None
            return None

        def walk(node, held: frozenset) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A nested def's body runs later (often on another
                # thread): locks held here are NOT held there.
                for d in node.decorator_list:
                    walk(d, held)
                for stmt in node.body:
                    walk(stmt, frozenset())
                return
            if isinstance(node, ast.Lambda):
                # Lambdas overwhelmingly run inline (sort/min keys,
                # callbacks invoked before the with exits) — keep the
                # held set. Deferred lambdas are a known blind spot the
                # runtime sanitizer covers.
                walk(node.body, held)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = set(held)
                for item in node.items:
                    g = lock_of(item.context_expr)
                    if g is not None:
                        facts.acquires.append(
                            _Acquire(g, item.context_expr.lineno,
                                     frozenset(new))
                        )
                        new.add(g)
                    else:
                        walk(item.context_expr, frozenset(new))
                for stmt in node.body:
                    walk(stmt, frozenset(new))
                return
            if isinstance(node, ast.Call):
                cs = _CallSite(
                    dotted=dotted_name(node.func),
                    lineno=node.lineno,
                    held=held,
                )
                if isinstance(node.func, ast.Attribute):
                    cs.receiver_lock = lock_of(node.func.value)
                key = resolve_call(
                    info, node, classname=fe.classname,
                    index=self.ctx.index,
                )
                if key is not None and key in self.table:
                    cs.callees = (key,)
                    cs.reach = (key,)
                    cs.resolved = True
                elif isinstance(node.func, ast.Attribute):
                    cs.reach = tuple(
                        self.by_name_reach.get(node.func.attr, ())
                    )
                    cands = tuple(
                        self.by_name.get(node.func.attr, ())
                    )
                    if len(cands) == 1:
                        cs.callees = cands
                if cs.dotted in ("threading.Thread", "Thread"):
                    cs.thread_target = self._thread_target(
                        info, fe, node
                    )
                facts.calls.append(cs)
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            if isinstance(node, (ast.Attribute, ast.Name)):
                g = access_of(node)
                if g is not None:
                    name = (
                        node.attr
                        if isinstance(node, ast.Attribute)
                        else node.id
                    )
                    facts.accesses.append(
                        _Access(g, name, node.lineno, held)
                    )
                if isinstance(node, ast.Attribute):
                    walk(node.value, held)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fe.node.body:
            walk(stmt, frozenset())
        return facts

    def _thread_target(self, info, fe, call: ast.Call):
        """Resolve ``threading.Thread(target=X)``: a (modname, funckey)
        when X names a function/method, else ("", "") meaning
        "unresolvable — treat the enclosing function as the entry"
        (nested-def targets are lexically inside it anyway)."""
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            t = kw.value
            if isinstance(t, ast.Name):
                key = (info.modname, t.id)
                if key in self.table:
                    return key
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and fe.classname
            ):
                key = (info.modname, f"{fe.classname}.{t.attr}")
                if key in self.table:
                    return key
            return ("", "")
        return ("", "")

    # -- call graph ----------------------------------------------------

    def _resolve_callees(self) -> None:
        # Incoming resolvable edges per callee; fallback edges are kept
        # separate and only used when a function has NO resolved
        # callers (a spurious name-match with an unlocked caller must
        # not dissolve a caller-holds helper's held set).
        self.incoming: dict[tuple, list] = {}
        self.incoming_fb: dict[tuple, list] = {}
        for key, facts in self.facts.items():
            for cs in facts.calls:
                sink = self.incoming if cs.resolved else self.incoming_fb
                for c in cs.callees:
                    sink.setdefault(c, []).append((key, cs.held))

    def _discover_roots(self) -> dict[tuple, str]:
        """Thread entry points → human-readable provenance."""
        roots: dict[tuple, str] = {}
        for mod, cls, funcname in self.entries_cfg:
            funckey = f"{cls}.{funcname}" if cls else funcname
            key = (mod, funckey)
            if key in self.facts:
                roots[key] = "configured thread entry"
        for modname, info in self.ctx.index.items():
            for cname, ci in info.classes.items():
                if any(
                    b == "Thread" or b.endswith(".Thread")
                    for b in ci.bases
                ) and "run" in ci.method_nodes:
                    key = (modname, f"{cname}.run")
                    if key in self.facts:
                        roots[key] = "threading.Thread subclass run()"
        for key, facts in self.facts.items():
            for cs in facts.calls:
                if cs.thread_target is None:
                    continue
                if cs.thread_target in self.facts:
                    roots.setdefault(
                        cs.thread_target, "threading.Thread target"
                    )
                else:
                    # Unresolvable (nested def / local): the closure
                    # body is lexically inside the spawning function.
                    roots.setdefault(
                        key, "spawns thread with local target"
                    )
        return roots

    def _entry_held_fixpoint(self) -> dict[tuple, frozenset]:
        """Held-on-entry per function: the intersection of (caller's
        entry-held ∪ lexical held at call site) over known call sites.
        Thread entries and functions with no known callers start
        empty. Monotone decreasing from the full lock universe."""
        eh: dict[tuple, frozenset] = {}
        sources: dict[tuple, list] = {}
        for key in self.facts:
            callers = self.incoming.get(key) or self.incoming_fb.get(key)
            if key in self.thread_roots or not callers:
                eh[key] = frozenset()
            else:
                sources[key] = callers
                eh[key] = self.universe
        changed = True
        while changed:
            changed = False
            for key, callers in sources.items():
                new = None
                for caller, held in callers:
                    tot = eh.get(caller, frozenset()) | held
                    new = tot if new is None else (new & tot)
                if new is not None and new != eh[key]:
                    eh[key] = new
                    changed = True
        return eh

    def _acquire_closure(self) -> dict[tuple, frozenset]:
        """Locks a function may acquire, transitively (lexical acquires
        plus every callee candidate's closure). Iterative fixed point —
        the call graph has cycles."""
        ac = {
            key: frozenset(a.guard for a in facts.acquires)
            for key, facts in self.facts.items()
        }
        changed = True
        while changed:
            changed = False
            for key, facts in self.facts.items():
                cur = ac[key]
                for cs in facts.calls:
                    for c in cs.callees:
                        cur = cur | ac.get(c, frozenset())
                if cur != ac[key]:
                    ac[key] = cur
                    changed = True
        return ac

    def total_held(self, key: tuple, lexical: frozenset) -> frozenset:
        return self.entry_held.get(key, frozenset()) | lexical

    def path_of(self, key: tuple):
        return self.ctx.index[key[0]].path


def _analysis(ctx: Context) -> LockAnalysis:
    a = getattr(ctx, "_gl_lock_analysis", None)
    if a is None or a.ctx is not ctx:
        a = LockAnalysis(ctx)
        ctx._gl_lock_analysis = a
    return a


@register
class LockGuardRule(Rule):
    id = "GL-LOCK-GUARD"
    title = "guarded state must be accessed under its declared lock"
    rationale = (
        "The serving stack's scheduler/autoscaler/residency triangle "
        "shares dicts across daemon worker threads, the engine pump, "
        "and the tick thread. A guarded-attribute access outside its "
        "``with <lock>`` is a torn read or lost update waiting for "
        "load; the guards table makes 'which lock protects this' a "
        "checked declaration instead of tribal knowledge."
    )
    fixtures = {
        "pkg/mod.py": (
            "import threading\n"
            "\n"
            "class Worker(threading.Thread):\n"
            "    def __init__(self):\n"
            "        super().__init__()\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = {}\n"
            "    def run(self):\n"
            "        with self._lock:\n"
            "            self._state['a'] = 1\n"
            "        self._state['b'] = 2\n"
        ),
    }
    fixture_config = {
        "package": "pkg",
        "lock_guards": ["pkg.mod:Worker._lock=_state"],
        "lock_thread_entries": [],
        "lock_blocking_calls": [],
    }

    def check(self, ctx: Context) -> None:
        an = _analysis(ctx)
        if an.error is not None:
            return  # GL-CONFIG reports the malformed table
        reachable: dict[tuple, str] = {}
        queue = list(an.thread_roots.items())
        while queue:
            key, provenance = queue.pop()
            if key in reachable:
                continue
            reachable[key] = provenance
            entry_name = an.table[key].qualname
            for cs in an.facts[key].calls:
                for c in cs.reach:
                    if c not in reachable:
                        queue.append((c, f"via {entry_name}"))
        for key, provenance in reachable.items():
            fe = an.table[key]
            if fe.name in _CTOR_NAMES:
                continue
            facts = an.facts[key]
            seen: set[tuple] = set()
            for acc in facts.accesses:
                held = an.total_held(key, acc.held)
                if acc.guard in held:
                    continue
                dedup = (acc.lineno, acc.attr)
                if dedup in seen:
                    continue
                seen.add(dedup)
                ctx.report(
                    self.id,
                    an.path_of(key),
                    acc.lineno,
                    f"{fe.qualname} accesses {acc.attr!r} without "
                    f"holding {acc.guard} (thread-reachable: "
                    f"{provenance}); wrap in 'with' or add a reasoned "
                    "disable for a deliberate lock-free path",
                )


@register
class LockOrderRule(Rule):
    id = "GL-LOCK-ORDER"
    title = "the static lock acquisition-order graph must be acyclic"
    rationale = (
        "Two threads taking the same pair of locks in opposite orders "
        "is the canonical deadlock, and nothing about either call site "
        "looks wrong in isolation. The static order graph (nested "
        "withs propagated through the call graph) proves a global "
        "hierarchy exists; the discovered order lands in --json as the "
        "one canonical hierarchy the runtime lockdep sanitizer and "
        "docs/locking.md share."
    )
    fixtures = {
        "pkg/mod.py": (
            "import threading\n"
            "\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "\n"
            "def forward():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "\n"
            "def backward():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"
        ),
    }
    fixture_config = {
        "package": "pkg",
        "lock_guards": ["pkg.mod:A=", "pkg.mod:B="],
        "lock_thread_entries": [],
        "lock_blocking_calls": [],
    }

    def check(self, ctx: Context) -> None:
        an = _analysis(ctx)
        if an.error is not None:
            return
        # (held → acquired) edges with one example site each.
        edges: dict[tuple[str, str], tuple[str, int]] = {}

        def add_edge(h: str, a: str, key: tuple, lineno: int) -> None:
            if h == a:  # reentrant re-acquire (RLock) is not an order
                return
            if (h, a) not in edges:
                rel = an.path_of(key).relative_to(ctx.repo).as_posix()
                edges[(h, a)] = (rel, lineno)

        for key, facts in an.facts.items():
            base = an.entry_held.get(key, frozenset())
            for acq in facts.acquires:
                for h in base | acq.held:
                    add_edge(h, acq.guard, key, acq.lineno)
            for cs in facts.calls:
                held = base | cs.held
                if not held:
                    continue
                for c in cs.callees:
                    for a in an.acq_closure.get(c, ()):
                        for h in held:
                            add_edge(h, a, key, cs.lineno)

        adj: dict[str, set[str]] = {}
        for (h, a) in edges:
            adj.setdefault(h, set()).add(a)

        # Cycle detection + topological order (DFS, deterministic).
        order: list[str] = []
        state: dict[str, int] = {}  # 1 = on stack, 2 = done
        cycles: list[list[str]] = []
        stack: list[str] = []

        def visit(n: str) -> None:
            state[n] = 1
            stack.append(n)
            for m in sorted(adj.get(n, ())):
                if state.get(m) == 1:
                    cycles.append(stack[stack.index(m):] + [m])
                elif m not in state:
                    visit(m)
            stack.pop()
            state[n] = 2
            order.append(n)

        nodes = sorted(
            set(an.universe)
            | {n for e in edges for n in e}
        )
        for n in nodes:
            if n not in state:
                visit(n)
        order.reverse()

        for cyc in cycles:
            sites = [
                f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cyc, cyc[1:])
                if (a, b) in edges
            ]
            first = edges.get((cyc[0], cyc[1]))
            path = first[0] if first else "pyproject.toml"
            line = first[1] if first else 1
            ctx.report(
                self.id,
                ctx.repo / path,
                line,
                "lock acquisition-order cycle "
                + " -> ".join(cyc)
                + " ("
                + "; ".join(sites)
                + ") — pick one global order and restructure the "
                "odd acquisition out",
            )
        if ctx.full_run:
            ctx.artifacts["lock_order"] = order
            ctx.artifacts["lock_edges"] = {
                f"{h}->{a}": f"{site}:{line}"
                for (h, a), (site, line) in sorted(edges.items())
            }


@register
class LockBlockingRule(Rule):
    id = "GL-LOCK-BLOCKING"
    title = "no indefinite/device-scale blocking under a tracked lock"
    rationale = (
        "A sleep, fsync, subprocess read, device sync, or engine chat "
        "dispatch made while holding a hot-path lock turns every other "
        "thread's microsecond acquire into a device-scale stall — the "
        "exact bug PR 15's review fixed by hand when the GB-scale "
        "demotion gather ran under the engine lock. Waiting on a "
        "DIFFERENT lock's condition while holding one is the same "
        "hazard with deadlock on top."
    )
    fixtures = {
        "pkg/mod.py": (
            "import threading\n"
            "import time\n"
            "\n"
            "L = threading.Lock()\n"
            "\n"
            "def slow_path():\n"
            "    with L:\n"
            "        time.sleep(1.0)\n"
        ),
    }
    fixture_config = {
        "package": "pkg",
        "lock_guards": ["pkg.mod:L="],
        "lock_thread_entries": [],
        "lock_blocking_calls": ["time.sleep"],
    }

    def check(self, ctx: Context) -> None:
        an = _analysis(ctx)
        if an.error is not None:
            return
        patterns = ctx.cfg.lock_blocking_calls
        for key, facts in an.facts.items():
            fe = an.table[key]
            for cs in facts.calls:
                held = an.total_held(key, cs.held)
                if not held:
                    continue
                last = cs.dotted.rsplit(".", 1)[-1]
                hit = None
                for p in patterns:
                    if "." in p:
                        if cs.dotted == p or cs.dotted.endswith("." + p):
                            hit = p
                            break
                    elif last == p:
                        hit = p
                        break
                if hit is None:
                    continue
                if last == "wait" and cs.receiver_lock is not None:
                    # Condition.wait on the held lock's OWN condition
                    # releases it while waiting — that is the sanctioned
                    # pattern. Still holding anything else is the bug.
                    rest = held - {cs.receiver_lock}
                    if not rest:
                        continue
                    ctx.report(
                        self.id,
                        an.path_of(key),
                        cs.lineno,
                        f"{fe.qualname} waits on {cs.receiver_lock}'s "
                        f"condition while still holding "
                        f"{', '.join(sorted(rest))} — the wait only "
                        "releases its own lock; this blocks every "
                        "acquirer of the others",
                    )
                    continue
                ctx.report(
                    self.id,
                    an.path_of(key),
                    cs.lineno,
                    f"{fe.qualname} calls {cs.dotted}() while holding "
                    f"{', '.join(sorted(held))} (blocking pattern "
                    f"{hit!r}) — move the blocking work outside the "
                    "lock or add a reasoned disable",
                )
