"""GL-REFCOUNT — allocator acquires must reach a release on all paths.

The paged KV pool is ref-counted (engine/kvcache.py): a sequence's pages
return to the free list only when every reference drops. A
``new_sequence`` / ``adopt`` / ``cache_ref`` whose owner then raises
before any ``free_sequence`` / ``cache_unref`` runs is a silent leak —
the pool shrinks by a few pages per fault until admissions start
deferring forever. PRs 1-3 made exception paths *routine* (chaos seams,
fault isolation, timeout expiry), so "it only leaks when something
throws" means "it leaks in production".

Intraprocedural path check, per function in the configured modules
(``refcount_modules``): every acquisition call must be covered by a
``try`` whose ``except``/``finally`` bodies call the matching release —
either the acquisition sits inside that try's body, or the try is the
IMMEDIATELY NEXT statement after the acquisition's (the
acquire-then-guard idiom ``_start_admission`` uses; any intervening
statement is a window where a raise leaks, so it breaks the guard).
Functions that only transfer ownership (registering the page/sequence
in a structure another path releases) suppress with a reason naming the
releasing path.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register


def _method_name(call: ast.Call) -> str:
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else ""


def _calls_release(body_nodes: list[ast.stmt], release: str) -> bool:
    for stmt in body_nodes:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and _method_name(sub) == release:
                return True
    return False


def _child_blocks(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if (
            isinstance(block, list)
            and block
            and isinstance(block[0], ast.stmt)
        ):
            yield block
    for handler in getattr(stmt, "handlers", []):
        if handler.body:
            yield handler.body


_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign)
# Single-pass compounds: control leaving their last statement falls
# straight through to the next sibling, so tail position propagates.
# Loops do NOT qualify (a later iteration's raise leaks the earlier
# iteration's acquire) and neither does a non-guard try (its handlers
# run in between).
_TAIL_TRANSPARENT = (ast.If, ast.With, ast.AsyncWith)


def _scan_block(
    block: list[ast.stmt], line: int, guard_ids: set[int]
) -> tuple[bool, bool] | None:
    """Locate the acquire at ``line`` within ``block`` (recursively) and
    decide (protected, tail):

    - protected: the acquire sits inside a guard try's BODY, or its
      statement chain is immediately followed by a guard try with no
      intervening statement (tail position all the way up);
    - tail: nothing can execute between the acquire and this block's
      fall-through — the parent may still find a guard as the next
      sibling.

    None when ``line`` is not in this block.
    """
    for i, stmt in enumerate(block):
        lo = stmt.lineno
        hi = getattr(stmt, "end_lineno", lo)
        if not lo <= line <= hi:
            continue
        next_is_guard = (
            i + 1 < len(block)
            and isinstance(block[i + 1], ast.Try)
            and id(block[i + 1]) in guard_ids
        )
        if isinstance(stmt, ast.Try) and id(stmt) in guard_ids:
            body_lo = stmt.body[0].lineno
            body_hi = getattr(
                stmt.body[-1], "end_lineno", stmt.body[-1].lineno
            )
            if body_lo <= line <= body_hi:
                return (True, False)
        sub = None
        for child in _child_blocks(stmt):
            r = _scan_block(child, line, guard_ids)
            if r is not None:
                sub = r
                break
        if sub is None:
            # The acquire sits directly in this statement — a simple
            # statement, or a compound's header/test (never tail: the
            # compound's own body runs before any sibling guard).
            simple = isinstance(stmt, _SIMPLE_STMTS)
            if simple and next_is_guard:
                return (True, True)
            return (False, simple and i == len(block) - 1)
        protected, tail = sub
        if protected:
            return (True, False)
        if tail and isinstance(stmt, _TAIL_TRANSPARENT):
            if next_is_guard:
                return (True, True)
            return (False, i == len(block) - 1)
        return (False, False)
    return None


@register
class RefcountRule(Rule):
    id = "GL-REFCOUNT"
    title = "allocator acquires must be released on exception paths"
    rationale = (
        "A missed free on a raise path is an invisible leak in a "
        "ref-counted pool: no crash, no wrong token, just a pool that "
        "monotonically shrinks every fault until admission stalls."
    )
    fixtures = {
        "pkg/leaky.py": (
            "def admit(allocator, seq_id, tokens):\n"
            "    allocator.new_sequence(seq_id)\n"
            "    allocator.extend(seq_id, len(tokens))  # can raise\n"
            "    return seq_id\n"
        ),
    }
    fixture_config = {"refcount_modules": ["pkg.leaky"]}

    def check(self, ctx: Context) -> None:
        pairs = ctx.cfg.acquire_release()
        for modname in ctx.cfg.refcount_modules:
            info = ctx.index.get(modname)
            if info is None:
                continue
            for node in ast.walk(info.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self._check_function(ctx, info, node, pairs)

    def _check_function(self, ctx, info, fn, pairs) -> None:
        # Tries (anywhere in fn) whose handlers/finally release, per
        # release method.
        guards: dict[str, set[int]] = {}  # release -> guard-try ids
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try):
                continue
            for release in set(pairs.values()):
                handler_bodies: list[ast.stmt] = list(node.finalbody)
                for h in node.handlers:
                    handler_bodies += h.body
                if _calls_release(handler_bodies, release):
                    guards.setdefault(release, set()).add(id(node))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            acquire = _method_name(node)
            release = pairs.get(acquire)
            if release is None:
                continue
            r = _scan_block(
                fn.body, node.lineno, guards.get(release, set())
            )
            protected = r is not None and r[0]
            if not protected:
                ctx.report(
                    "GL-REFCOUNT",
                    info.path,
                    node.lineno,
                    f"{acquire}() in {fn.name} has no except/finally "
                    f"path calling {release}() covering it — an "
                    "exception between the acquire and the release "
                    "leaks the reference; guard it (acquire "
                    f"immediately followed by try/except: {release}; "
                    "raise) or suppress with a reason naming the owner "
                    "that releases it",
                )

