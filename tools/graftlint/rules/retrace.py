"""GL-RETRACE — jit call sites must keep the compile-shape set bounded.

Every distinct value of a ``static_argnames`` parameter — and every
Python scalar that jit weak-types into the trace — is a separate
compiled program. One stray dynamic scalar (a raw ``len(prompt)``, an
unbucketed remaining-token count) turns the fixed pow2 program set
PR 2 established into a retrace per request: the host-overhead-bound
regime where TPU serving walls go to die.

At every statically resolvable call to a known jit entry point
(discovered from ``@jax.jit`` / ``partial(jax.jit, …)`` decorations and
``name = partial(jax.jit, …)(impl)`` wrappings):

- a **static** argument must be *bounded*: a literal, an attribute read
  (``self.chunk`` — fixed per instance), a module-level constant, a
  value derived from an array's ``.shape`` (already a compiled shape),
  or a call to an approved bucketer (``retrace_bucketers`` config:
  ``bucket_length`` & friends). Provably-dynamic expressions — direct
  ``len()/int()/float()`` results, arithmetic on them, or locals
  assigned from such — are findings.
- a **traced** argument must not be a bare host-scalar call
  (``int(x)``, ``len(x)`` …): wrap it (``jnp.int32(x)``) so it enters
  the program as a device operand, or declare it static and bucket it.

Names whose provenance is unknown (enclosing-function parameters,
loop-carried state) are skipped — the rule is conservative by design;
the discipline is enforced where the scalar is *produced*.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.index import FuncSig, JitEntry, ModuleInfo, dotted_name

_HOST_SCALAR_FNS = {"len", "int", "float", "bool", "ord", "round"}


def _walk_own_scope(fn: ast.AST):
    """ast.walk restricted to ``fn``'s own scope: does not descend into
    nested FunctionDef/AsyncFunctionDef/Lambda bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


def _jit_callables(
    ctx: Context,
) -> dict[str, tuple[JitEntry, FuncSig | None]]:
    """dotted name -> entry, plus per-module local/imported aliases are
    resolved at the call site (see _resolve_entry)."""
    out: dict[str, tuple[JitEntry, FuncSig | None]] = {}
    for modname, info in ctx.index.items():
        for entry in info.jit_entries.values():
            sig = info.functions.get(entry.impl)
            out[f"{modname}.{entry.name}"] = (entry, sig)
    return out


def _resolve_entry(
    info: ModuleInfo,
    func: ast.expr,
    table: dict[str, tuple[JitEntry, FuncSig | None]],
):
    """The (entry, sig) a call's func expression statically names."""
    if isinstance(func, ast.Name):
        name = func.id
        hit = table.get(f"{info.modname}.{name}")
        if hit:
            return hit
        if name in info.from_imports:
            src_mod, orig = info.from_imports[name]
            return table.get(f"{src_mod}.{orig}")
    elif isinstance(func, ast.Attribute) and isinstance(
        func.value, ast.Name
    ):
        target = info.mod_imports.get(func.value.id)
        if target is not None:
            return table.get(f"{target}.{func.attr}")
    return None


class _LocalFlow:
    """One-level provenance for locals of the enclosing function:
    name -> "bounded" | "dynamic" | absent (unknown). Nested function
    bodies have their own scope — their assignments must not poison a
    same-named outer local — so the walk stops at inner defs."""

    def __init__(self, fn: ast.AST | None, bucketers: set[str]):
        self.kinds: dict[str, str] = {}
        self.bucketers = bucketers
        if fn is None:
            return
        for node in _walk_own_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self._note(t.id, node.value)
                elif isinstance(t, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in t.elts
                ):
                    # B, S = tokens.shape — shape dims are existing
                    # compile shapes, so all targets are bounded.
                    if self._expr_kind(node.value) == "bounded":
                        for e in t.elts:
                            self.kinds[e.id] = "bounded"

    def _note(self, name: str, value: ast.expr) -> None:
        kind = self._expr_kind(value)
        prev = self.kinds.get(name)
        # A name rebound with mixed provenance degrades to unknown
        # (flow-insensitive join), except dynamic which is sticky.
        if prev == "dynamic" or kind == "dynamic":
            self.kinds[name] = "dynamic"
        elif prev is None:
            self.kinds[name] = kind
        elif prev != kind:
            self.kinds.pop(name, None)

    def _expr_kind(self, expr: ast.expr) -> str:
        """"bounded" | "dynamic" | "unknown" for a value expression."""
        if isinstance(expr, ast.Constant):
            return "bounded"
        if isinstance(expr, ast.UnaryOp):
            return self._expr_kind(expr.operand)
        if isinstance(expr, ast.Attribute):
            # obj.attr reads: fixed per object (self.chunk, cfg.depth)
            # or an array's .shape — both bounded.
            return "bounded"
        if isinstance(expr, ast.Subscript):
            # x.shape[0], table[i] — bounded iff the base is.
            return self._expr_kind(expr.value)
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            tail = name.rsplit(".", 1)[-1] if name else ""
            if tail in self.bucketers:
                return "bounded"
            if name in _HOST_SCALAR_FNS:
                return "dynamic"
            return "unknown"
        if isinstance(expr, ast.BinOp):
            left = self._expr_kind(expr.left)
            right = self._expr_kind(expr.right)
            if "dynamic" in (left, right):
                return "dynamic"
            if left == right == "bounded":
                return "bounded"
            return "unknown"
        return "unknown"

    def kind_of(self, expr: ast.expr) -> str:
        if isinstance(expr, ast.Name):
            return self.kinds.get(expr.id, "unknown")
        return self._expr_kind(expr)


@register
class RetraceRule(Rule):
    id = "GL-RETRACE"
    title = "jit static args bounded; traced args never bare host scalars"
    rationale = (
        "jit compiles one program per static-arg value and per weak-"
        "typed Python scalar: an unbucketed dynamic length is a retrace "
        "storm — compile time on the serving path, once per request."
    )
    fixtures = {
        "pkg/calls.py": (
            "from functools import partial\n"
            "import jax\n"
            "import jax.numpy as jnp\n"
            "\n"
            "def _impl(x, n, *, chunk):\n"
            "    return x\n"
            "\n"
            "step = partial(jax.jit, static_argnames=('chunk',))(_impl)\n"
            "\n"
            "def drive(x, xs):\n"
            "    step(x, jnp.int32(0), chunk=256)        # fine\n"
            "    step(x, jnp.int32(0), chunk=len(xs))    # retrace storm\n"
            "    step(x, len(xs), chunk=256)             # host scalar\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        table = _jit_callables(ctx)
        bucketers = set(ctx.cfg.retrace_bucketers)
        for info in ctx.index.values():
            self._check_module(ctx, info, table, bucketers)

    def _check_module(self, ctx, info, table, bucketers) -> None:
        # Map each call to its innermost enclosing function: visit defs
        # outermost-first (ast.walk order by lineno) so nested defs
        # overwrite their own calls and each call keeps its innermost
        # owner for local-flow analysis.
        enclosing: dict[int, ast.AST] = {}
        defs = sorted(
            (
                n
                for n in ast.walk(info.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ),
            key=lambda f: f.lineno,
        )
        for fn in defs:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    enclosing[id(sub)] = fn

        flows: dict[int, _LocalFlow] = {}
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _resolve_entry(info, node.func, table)
            if hit is None:
                continue
            entry, sig = hit
            if sig is None:
                continue
            owner = enclosing.get(id(node))
            key = id(owner) if owner is not None else 0
            if key not in flows:
                flows[key] = _LocalFlow(owner, bucketers)
            flow = flows[key]
            self._check_call(ctx, info, node, entry, sig, flow)

    def _check_call(self, ctx, info, node, entry, sig, flow) -> None:
        static = set(entry.static_argnames)

        def warn(arg_node: ast.AST, param: str, msg: str) -> None:
            ctx.report(
                "GL-RETRACE",
                info.path,
                arg_node.lineno,
                f"{entry.name}(... {param}=...) {msg}",
            )

        if any(isinstance(a, ast.Starred) for a in node.args) or any(
            kw.arg is None for kw in node.keywords
        ):
            return  # *args/**kwargs: not statically resolvable
        bound: list[tuple[str, ast.expr]] = []
        for i, arg in enumerate(node.args):
            if i < len(sig.pos_names):
                bound.append((sig.pos_names[i], arg))
        for kw in node.keywords:
            bound.append((kw.arg, kw.value))

        for param, value in bound:
            kind = flow.kind_of(value)
            if param in static:
                if kind == "dynamic":
                    warn(
                        value,
                        param,
                        "passes a dynamic Python scalar to a static "
                        "arg — every distinct value recompiles; bucket "
                        "it (bucket_length & friends) or fix it per "
                        "call site",
                    )
            else:
                # Traced param: a direct host-scalar call weak-types a
                # fresh Python scalar into the trace.
                if (
                    isinstance(value, ast.Call)
                    and dotted_name(value.func) in _HOST_SCALAR_FNS
                ):
                    warn(
                        value,
                        param,
                        "passes a bare host scalar to a traced arg — "
                        "wrap it (jnp.int32/jnp.asarray) or declare it "
                        "static and bucket it",
                    )
