"""GL-SYNC — no host sync in the continuous batcher outside sanctioned
sync points (interprocedural since graftlint v2).

The pipelined drive loop's whole contract (docs/perf.md) is that the
host never blocks on the device between chunks: it dispatches against a
trailing snapshot and syncs only at admission handoff, slot completion,
fault decisions, and timeout expiry. astlint's rule 4 guarded the
EXPLICIT sync (``jax.block_until_ready``); this rule also catches the
implicit ones that stall identically but look innocent:

- ``np.asarray(x)`` / ``numpy.asarray(x)`` on a device value
- ``jax.device_get(x)``
- ``x.item()`` on a device value
- ``int(x)`` / ``float(x)`` / ``bool(x)`` on a device value
- truthiness of a device value (``if x.any():`` blocks the host)

"Device value" is decided by seed taint (``sync_device_attrs`` —
``self.active``, ``adm.pads`` …; ``sync_device_names`` for the few
container-laundered locals) plus the dataflow engine
(tools/graftlint/dataflow.py): taint propagates through local
assignments, through calls whose arguments carry it
(``read_tokens(self.pool, …)``), across return summaries
(``self._dispatch_spec()`` returns device counts), and into helper
parameters at call sites — extracting a batcher snippet into a helper
no longer launders its device values. Methods in ``sync_allowlist``
(the sanctioned blanket-sync points) are exempt; individual sanctioned
fetches elsewhere carry an inline
``# graftlint: disable=GL-SYNC -- <why this point may sync>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.dataflow import DeviceTaint, FuncEntry

_NUMPY_NAMES = {"np", "numpy"}


def _is_identity_test(expr: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` — a pure host-side identity
    check on the Python reference; no device value is materialized, so
    it is not a sync no matter how tainted ``x`` is."""
    return (
        isinstance(expr, ast.Compare)
        and all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops)
        and all(
            isinstance(c, ast.Constant) and c.value is None
            for c in expr.comparators
        )
    )


@register
class SyncRule(Rule):
    id = "GL-SYNC"
    title = "no host sync in the batcher outside sanctioned points"
    rationale = (
        "One stray np.asarray/.item()/bool() on a device array inside "
        "the drive loop serializes host and device again — the exact "
        "host-overhead-bound stall the pipelined loop exists to remove. "
        "The implicit forms don't say 'sync' anywhere, so only a "
        "machine check keeps them out — and since the interprocedural "
        "port, extracting the fetch into a helper doesn't hide it."
    )
    fixtures = {
        "pkg/sched.py": (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "\n"
            "def gather(pool, idx):\n"
            "    return pool[idx]\n"
            "\n"
            "class ContinuousBatcher:\n"
            "    def _advance_admission(self):\n"
            "        jax.block_until_ready(self.active)  # allowlisted\n"
            "    def _counts(self):\n"
            "        return jnp.stack([self.n_emitted])\n"
            "    def _extracted_helper(self, buf):\n"
            "        return np.asarray(buf)\n"
            "    def _hot_loop(self):\n"
            "        jax.block_until_ready(self.active)\n"
            "        a = np.asarray(self.active)\n"
            "        n = int(self.n_emitted[0])\n"
            "        v = self.out_buf.item()\n"
            "        g = jax.device_get(self.pool)\n"
            "        rows = gather(self.pool, 0)\n"
            "        b = rows.item()\n"
            "        counts = self._counts()\n"
            "        c = np.asarray(counts)\n"
            "        d = self._extracted_helper(self.out_buf)\n"
            "        if self.active.any():\n"
            "            pass\n"
            "        return a, n, v, g, b, c, d\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        cfg = ctx.cfg
        allow = set(cfg.sync_allowlist)
        taint = DeviceTaint(
            ctx.index,
            set(cfg.sync_device_attrs),
            set(cfg.sync_device_names),
            depth=cfg.dataflow_depth,
        )
        roots: list[FuncEntry] = []
        sync_mods: set[str] = set()
        for info in ctx.index.values():
            ci = info.classes.get(cfg.sync_class)
            if ci is None:
                continue
            sync_mods.add(info.modname)
            for name, node in ci.method_nodes.items():
                if name in allow:
                    continue
                roots.append(
                    FuncEntry(info.modname, cfg.sync_class, name, node)
                )
        if not roots:
            return

        # Helper extraction must not launder taint: seed helper params
        # from tainted call-site args — same-module functions and
        # sync-class methods only, never jit-traced bodies (device
        # programs are not host code) and never allowlisted methods.
        jit_bodies = {
            (m, n)
            for m in sync_mods
            for e in ctx.index[m].jit_entries.values()
            for n in (e.name, e.impl)
        }

        def accept(entry: FuncEntry) -> bool:
            if entry.modname not in sync_mods or entry.name in allow:
                return False
            if entry.classname and entry.classname != cfg.sync_class:
                return False
            if not entry.classname and (
                (entry.modname, entry.name) in jit_bodies
            ):
                return False
            return True

        helpers = taint.propagate_params(roots, accept)
        root_keys = {r.key for r in roots}
        checked = roots + [h for h in helpers if h.key not in root_keys]
        for entry in checked:
            self._check_function(ctx, entry, taint)

    def _check_function(
        self, ctx: Context, entry: FuncEntry, taint: DeviceTaint
    ) -> None:
        info = ctx.index[entry.modname]
        where = (
            f"{entry.classname}.{entry.name}"
            if entry.classname
            else f"helper {entry.name}"
        )

        def tainted(expr: ast.expr) -> bool:
            return taint.tainted(expr, entry)

        def warn(node: ast.AST, what: str) -> None:
            ctx.report(
                "GL-SYNC",
                info.path,
                node.lineno,
                f"{what} in {where} syncs the "
                "host outside the sanctioned sync points "
                f"({', '.join(sorted(ctx.cfg.sync_allowlist))}); fetch at "
                "a sanctioned point or suppress with a reason",
            )

        for sub in ast.walk(entry.node):
            if isinstance(sub, ast.Call):
                f = sub.func
                # Explicit: jax.block_until_ready / block_until_ready.
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "block_until_ready"
                ) or (
                    isinstance(f, ast.Name) and f.id == "block_until_ready"
                ):
                    warn(sub, "jax.block_until_ready")
                # jax.device_get(x): a fetch by definition.
                elif isinstance(f, ast.Attribute) and f.attr == "device_get":
                    warn(sub, "jax.device_get")
                # np.asarray(device) — device→host copy blocks.
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _NUMPY_NAMES
                    and sub.args
                    and tainted(sub.args[0])
                ):
                    warn(sub, "np.asarray on a device value")
                # int()/float()/bool() on a device value.
                elif (
                    isinstance(f, ast.Name)
                    and f.id in ("int", "float", "bool")
                    and sub.args
                    and tainted(sub.args[0])
                ):
                    warn(sub, f"{f.id}() on a device value")
                # x.item() on a device value.
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "item"
                    and not sub.args
                    and tainted(f.value)
                ):
                    warn(sub, ".item() on a device value")
            elif (
                isinstance(sub, (ast.If, ast.While))
                and tainted(sub.test)
                and not _is_identity_test(sub.test)
            ):
                # Truthiness of a device expression blocks the host.
                # (int()/bool()/np.asarray inside the test are already
                # reported above; this catches the bare `if x.any():`.)
                warn(sub.test, "truthiness of a device value")
