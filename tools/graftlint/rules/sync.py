"""GL-SYNC — no host sync in the continuous batcher outside sanctioned
sync points.

The pipelined drive loop's whole contract (docs/perf.md) is that the
host never blocks on the device between chunks: it dispatches against a
trailing snapshot and syncs only at admission handoff, slot completion,
fault decisions, and timeout expiry. astlint's rule 4 guarded the
EXPLICIT sync (``jax.block_until_ready``); this rule also catches the
implicit ones that stall identically but look innocent:

- ``np.asarray(x)`` / ``numpy.asarray(x)`` on a device value
- ``jax.device_get(x)``
- ``x.item()`` on a device value
- ``int(x)`` / ``float(x)`` / ``bool(x)`` on a device value
- truthiness of a device value (``if x.any():`` blocks the host)

"Device value" is decided by a configured taint set: attribute names
that hold device arrays inside the sync class (``sync_device_attrs`` —
``self.active``, ``adm.pads`` …) and bare local names known to be
fetched device results (``sync_device_names``). Methods in
``sync_allowlist`` (the sanctioned blanket-sync points) are exempt;
individual sanctioned fetches elsewhere carry an inline
``# graftlint: disable=GL-SYNC -- <why this point may sync>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register

_NUMPY_NAMES = {"np", "numpy"}


def _is_identity_test(expr: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` — a pure host-side identity
    check on the Python reference; no device value is materialized, so
    it is not a sync no matter how tainted ``x`` is."""
    return (
        isinstance(expr, ast.Compare)
        and all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops)
        and all(
            isinstance(c, ast.Constant) and c.value is None
            for c in expr.comparators
        )
    )


def _is_device_tainted(
    expr: ast.expr, device_attrs: set[str], device_names: set[str]
) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr in device_attrs:
            return True
        if isinstance(sub, ast.Name) and sub.id in device_names:
            return True
    return False


@register
class SyncRule(Rule):
    id = "GL-SYNC"
    title = "no host sync in the batcher outside sanctioned points"
    rationale = (
        "One stray np.asarray/.item()/bool() on a device array inside "
        "the drive loop serializes host and device again — the exact "
        "host-overhead-bound stall the pipelined loop exists to remove. "
        "The implicit forms don't say 'sync' anywhere, so only a "
        "machine check keeps them out."
    )
    fixtures = {
        "pkg/sched.py": (
            "import jax\n"
            "import numpy as np\n"
            "\n"
            "class ContinuousBatcher:\n"
            "    def _advance_admission(self):\n"
            "        jax.block_until_ready(self.active)  # allowlisted\n"
            "    def _hot_loop(self):\n"
            "        jax.block_until_ready(self.active)\n"
            "        a = np.asarray(self.active)\n"
            "        n = int(self.n_emitted[0])\n"
            "        v = self.out_buf.item()\n"
            "        g = jax.device_get(self.pool)\n"
            "        if self.active.any():\n"
            "            pass\n"
            "        return a, n, v, g\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        cfg = ctx.cfg
        device_attrs = set(cfg.sync_device_attrs)
        device_names = set(cfg.sync_device_names)
        allow = set(cfg.sync_allowlist)
        for info in ctx.index.values():
            for node in info.tree.body:
                if (
                    not isinstance(node, ast.ClassDef)
                    or node.name != cfg.sync_class
                ):
                    continue
                for method in node.body:
                    if not isinstance(
                        method, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    if method.name in allow:
                        continue
                    self._check_method(
                        ctx, info, method, device_attrs, device_names
                    )

    def _check_method(
        self, ctx, info, method, device_attrs, device_names
    ) -> None:
        def tainted(expr: ast.expr) -> bool:
            return _is_device_tainted(expr, device_attrs, device_names)

        def warn(node: ast.AST, what: str) -> None:
            ctx.report(
                "GL-SYNC",
                info.path,
                node.lineno,
                f"{what} in {ctx.cfg.sync_class}.{method.name} syncs the "
                "host outside the sanctioned sync points "
                f"({', '.join(sorted(ctx.cfg.sync_allowlist))}); fetch at "
                "a sanctioned point or suppress with a reason",
            )

        for sub in ast.walk(method):
            if isinstance(sub, ast.Call):
                f = sub.func
                # Explicit: jax.block_until_ready / block_until_ready.
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "block_until_ready"
                ) or (
                    isinstance(f, ast.Name) and f.id == "block_until_ready"
                ):
                    warn(sub, "jax.block_until_ready")
                # jax.device_get(x): a fetch by definition.
                elif isinstance(f, ast.Attribute) and f.attr == "device_get":
                    warn(sub, "jax.device_get")
                # np.asarray(device) — device→host copy blocks.
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _NUMPY_NAMES
                    and sub.args
                    and tainted(sub.args[0])
                ):
                    warn(sub, "np.asarray on a device value")
                # int()/float()/bool() on a device value.
                elif (
                    isinstance(f, ast.Name)
                    and f.id in ("int", "float", "bool")
                    and sub.args
                    and tainted(sub.args[0])
                ):
                    warn(sub, f"{f.id}() on a device value")
                # x.item() on a device value.
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "item"
                    and not sub.args
                    and tainted(f.value)
                ):
                    warn(sub, ".item() on a device value")
            elif (
                isinstance(sub, (ast.If, ast.While))
                and tainted(sub.test)
                and not _is_identity_test(sub.test)
            ):
                # Truthiness of a device expression blocks the host.
                # (int()/bool()/np.asarray inside the test are already
                # reported above; this catches the bare `if x.any():`.)
                warn(sub.test, "truthiness of a device value")
