"""GL-TRACE — no Python side effects inside jit-traced bodies.

A jit-traced function body runs ONCE per compile shape, at trace time.
``time.monotonic()`` there stamps the trace, not the step; ``print``
fires once and never again; mutating ``self``/globals/stats stores
writes during tracing and then silently stops. All of these "work" on
the first call and rot into wrong telemetry or stale constants.

Trace roots are discovered, not declared: functions decorated with
``jax.jit`` / ``partial(jax.jit, ...)``, impls wrapped via
``name = partial(jax.jit, ...)(impl)``, and kernels passed to
``pl.pallas_call``. The traced set is the transitive closure over
statically resolvable calls into the linted tree (same-module names,
from-imports, ``module.func``) — the fused program's shared bodies
(``_prefill_chunk_impl`` / ``_decode_chunk_impl``) are reached from
``fused_prefill_decode_chunk`` automatically.

Flagged inside a traced body:
- calls matching a configured impure prefix (``time.``, ``print``,
  stats stores, ``injector.fire`` …);
- assignment / augmented assignment to any attribute (``self.x = …``,
  ``obj.n += 1`` — trace-time mutation);
- ``global`` / ``nonlocal`` declarations.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.index import ModuleInfo, dotted_name


def _pallas_kernels(info: ModuleInfo) -> set[str]:
    """Local function names passed as the first arg to pl.pallas_call."""
    out: set[str] = set()
    for node in ast.walk(info.tree):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.endswith("pallas_call") and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    out.add(first.id)
                elif isinstance(first, ast.Call):
                    # functools.partial(kernel, ...) wrapping
                    inner = dotted_name(first.func)
                    if inner in ("functools.partial", "partial"):
                        if first.args and isinstance(
                            first.args[0], ast.Name
                        ):
                            out.add(first.args[0].id)
    return out


def traced_functions(ctx: Context) -> set[tuple[str, str]]:
    """(modname, funcname) closure of everything that traces."""
    roots: set[tuple[str, str]] = set()
    for modname, info in ctx.index.items():
        for entry in info.jit_entries.values():
            if entry.impl in info.func_nodes:
                roots.add((modname, entry.impl))
        for kernel in _pallas_kernels(info):
            if kernel in info.func_nodes:
                roots.add((modname, kernel))
    for dotted in ctx.cfg.trace_extra_roots:
        mod, _, fn = dotted.rpartition(".")
        if mod in ctx.index and fn in ctx.index[mod].func_nodes:
            roots.add((mod, fn))

    closure = set(roots)
    work = list(roots)
    while work:
        modname, fname = work.pop()
        info = ctx.index[modname]
        node = info.func_nodes[fname]
        for callee in _resolvable_callees(ctx, info, node):
            if callee not in closure:
                closure.add(callee)
                work.append(callee)
    return closure


def _resolvable_callees(
    ctx: Context, info: ModuleInfo, fn: ast.FunctionDef
) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in info.func_nodes and name != fn.name:
                out.append((info.modname, name))
            elif name in info.from_imports:
                src_mod, orig = info.from_imports[name]
                src = ctx.index.get(src_mod)
                if src is not None and orig in src.func_nodes:
                    out.append((src_mod, orig))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            target = info.mod_imports.get(f.value.id)
            if target is not None:
                src = ctx.index.get(target)
                if src is not None and f.attr in src.func_nodes:
                    out.append((target, f.attr))
    return out


@register
class TraceRule(Rule):
    id = "GL-TRACE"
    title = "no Python side effects inside jit-traced bodies"
    rationale = (
        "A host call inside a traced body executes at trace time only: "
        "timers stamp the compile, prints vanish after the first shape, "
        "stats-store updates count shapes instead of steps, and "
        "attribute writes bake one trace's value in forever."
    )
    fixtures = {
        "pkg/kern.py": (
            "import time\n"
            "from functools import partial\n"
            "import jax\n"
            "\n"
            "def _impl(x, counters):\n"
            "    t0 = time.monotonic()\n"
            "    print('tracing', t0)\n"
            "    counters.n_steps += 1\n"
            "    return x\n"
            "\n"
            "step = partial(jax.jit, donate_argnames=())(_impl)\n"
        ),
    }

    def check(self, ctx: Context) -> None:
        impure = list(ctx.cfg.trace_impure_calls)
        for modname, fname in sorted(traced_functions(ctx)):
            info = ctx.index[modname]
            fn = info.func_nodes[fname]
            self._check_body(ctx, info, fn, impure)

    def _check_body(self, ctx, info, fn, impure) -> None:
        def warn(node: ast.AST, what: str) -> None:
            ctx.report(
                "GL-TRACE",
                info.path,
                node.lineno,
                f"{what} inside jit-traced '{fn.name}' runs at trace "
                "time only (bakes a constant / fires once per compile "
                "shape); hoist it to the host caller",
            )

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name:
                    for prefix in impure:
                        # "time." / "stats.record_" are open prefixes;
                        # bare names ("print") match exactly or at a
                        # dotted boundary — never "print_report".
                        if (
                            name == prefix
                            or (
                                prefix[-1] in "._"
                                and name.startswith(prefix)
                            )
                            or name.startswith(prefix + ".")
                        ):
                            warn(node, f"call to {name}()")
                            break
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        warn(node, f"attribute write to {dotted_name(t)}")
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for e in t.elts:
                            if isinstance(e, ast.Attribute):
                                warn(
                                    node,
                                    f"attribute write to {dotted_name(e)}",
                                )
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                warn(node, f"{type(node).__name__.lower()} declaration")
