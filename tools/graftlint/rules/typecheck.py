"""astlint's original three type-error checks as registered rules.

GL-IMPORT  ``from <package>.<module> import NAME`` — NAME must be bound
           in the target module (def / class / assignment / re-export).
GL-ATTR    ``<module>.NAME`` attribute access on package modules
           imported as module objects — NAME must be bound there.
GL-ARITY   call arity + keyword validity for calls that statically
           resolve to a function, class constructor, or ``self.method``
           defined in the linted tree.

One visitor produces all three (the resolution state is shared); the
driver filters findings to the selected rule ids, and an idempotence
guard keeps ``--rule GL-IMPORT,GL-ARITY`` from double-walking.

Deliberately conservative, exactly like astlint: calls through
*args/**kwargs, decorated functions whose decorator is not known
signature-preserving, attribute chains through values, and anything not
statically resolvable are skipped.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import Context, Rule, register
from tools.graftlint.index import ClassInfo, FuncSig, ModuleInfo


class _Checker(ast.NodeVisitor):
    def __init__(self, info: ModuleInfo, ctx: Context):
        self.info = info
        self.ctx = ctx
        self.index = ctx.index
        # local name -> ("func", FuncSig) | ("class", ClassInfo)
        #            | ("module", ModuleInfo)
        self.resolved: dict[str, tuple[str, object]] = {}
        self.current_class: ClassInfo | None = None
        for name, sig in info.functions.items():
            self.resolved[name] = ("func", sig)
        for name, ci in info.classes.items():
            self.resolved[name] = ("class", ci)

    def _warn(self, rule: str, node: ast.AST, msg: str) -> None:
        self.ctx.report(rule, self.info.path, node.lineno, msg)

    # ---------------------------------------------------------- imports

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        from tools.graftlint.index import resolve_import_from

        target = resolve_import_from(self.info, node)
        tinfo = self.index.get(target)
        if tinfo is not None:
            for alias in node.names:
                if alias.name == "*":
                    continue
                # Submodule import (from pkg import engine) counts.
                if (
                    alias.name not in tinfo.bindings
                    and f"{target}.{alias.name}" not in self.index
                ):
                    self._warn(
                        "GL-IMPORT",
                        node,
                        f"'{alias.name}' is not defined in {target}",
                    )
                local = alias.asname or alias.name
                if alias.name in tinfo.functions:
                    self.resolved[local] = (
                        "func",
                        tinfo.functions[alias.name],
                    )
                elif alias.name in tinfo.classes:
                    self.resolved[local] = (
                        "class",
                        tinfo.classes[alias.name],
                    )
                elif f"{target}.{alias.name}" in self.index:
                    self.resolved[local] = (
                        "module",
                        self.index[f"{target}.{alias.name}"],
                    )
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name in self.index:
                local = alias.asname or alias.name.split(".")[0]
                if alias.asname or "." not in alias.name:
                    self.resolved[local] = (
                        "module",
                        self.index[alias.name],
                    )
        self.generic_visit(node)

    # ------------------------------------------------------ assignments

    def visit_Assign(self, node: ast.Assign) -> None:
        # A rebind shadows whatever we resolved — stop checking it.
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in self.resolved:
                self.resolved.pop(t.id, None)
        self.generic_visit(node)

    # ---------------------------------------------------------- classes

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self.current_class
        self.current_class = self.info.classes.get(node.name)
        self.generic_visit(node)
        self.current_class = prev

    # ------------------------------------------------------------ scopes

    def _shadowed_names(self, fn) -> set[str]:
        """Names this function rebinds locally: params plus local
        assignment/for/with/except targets (one level of flow analysis —
        enough to avoid false positives, not a full scope model)."""
        names: set[str] = set()
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            names.add(p.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)

        def add_target(t: ast.expr) -> None:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    add_target(e)
            elif isinstance(t, ast.Starred):
                add_target(t.value)

        body = getattr(fn, "body", [])
        if isinstance(body, ast.expr):  # Lambda
            body = [ast.Expr(body)]
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        add_target(t)
                elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                    add_target(sub.target)
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    add_target(sub.target)
                elif isinstance(sub, ast.NamedExpr):
                    add_target(sub.target)
                elif isinstance(sub, ast.comprehension):
                    add_target(sub.target)
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        if item.optional_vars is not None:
                            add_target(item.optional_vars)
                elif isinstance(sub, ast.ExceptHandler):
                    if sub.name:
                        names.add(sub.name)
                elif isinstance(
                    sub,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    names.add(sub.name)
                elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        if alias.name != "*":
                            names.add(
                                alias.asname or alias.name.split(".")[0]
                            )
                elif isinstance(sub, (ast.Global, ast.Nonlocal)):
                    # Declared non-local: the name is NOT shadowed.
                    names.difference_update(sub.names)
        return names

    def _visit_function_scope(self, node) -> None:
        shadowed = {
            n: self.resolved.pop(n)
            for n in self._shadowed_names(node)
            if n in self.resolved
        }
        self.generic_visit(node)
        self.resolved.update(shadowed)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function_scope(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function_scope(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_function_scope(node)

    # ------------------------------------------------------- attributes

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name):
            entry = self.resolved.get(node.value.id)
            if entry and entry[0] == "module":
                minfo: ModuleInfo = entry[1]  # type: ignore[assignment]
                if (
                    node.attr not in minfo.bindings
                    and f"{minfo.modname}.{node.attr}" not in self.index
                    and not node.attr.startswith("__")
                ):
                    self._warn(
                        "GL-ATTR",
                        node,
                        f"module '{minfo.modname}' has no attribute "
                        f"'{node.attr}'",
                    )
        self.generic_visit(node)

    # ------------------------------------------------------------ calls

    def _check_sig(self, node: ast.Call, sig: FuncSig, what: str) -> None:
        if not sig.checkable:
            return
        if any(isinstance(a, ast.Starred) for a in node.args) or any(
            kw.arg is None for kw in node.keywords
        ):
            return  # *args / **kwargs at the call site: not statically known
        self.ctx.n_checked_calls += 1
        n_pos_given = len(node.args)
        kw_given = {kw.arg for kw in node.keywords}
        # positional overflow
        if not sig.has_vararg and n_pos_given > sig.n_pos:
            self._warn(
                "GL-ARITY",
                node,
                f"{what} takes {sig.n_pos} positional args "
                f"but {n_pos_given} given",
            )
            return
        # unknown keywords
        if not sig.has_kwarg:
            valid = set(sig.pos_names) | set(sig.kwonly)
            for kw in kw_given:
                if kw not in valid:
                    self._warn(
                        "GL-ARITY",
                        node,
                        f"{what} got unexpected keyword '{kw}'",
                    )
        # missing required args: only keywords naming a REQUIRED
        # positional cover one (a keyword hitting an optional positional
        # must not mask a missing required arg, e.g. f(b=2) on f(a, b=1)).
        required_pos = sig.n_pos - sig.n_pos_defaults
        covered = n_pos_given + len(
            kw_given & set(sig.pos_names[n_pos_given:required_pos])
        )
        if covered < required_pos:
            self._warn(
                "GL-ARITY",
                node,
                f"{what} missing required args "
                f"({covered} of {required_pos} provided)",
            )
        for kw in sig.kwonly_required:
            if kw not in kw_given:
                self._warn(
                    "GL-ARITY",
                    node,
                    f"{what} missing required keyword-only '{kw}'",
                )

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name):
            entry = self.resolved.get(func.id)
            if entry:
                kind, obj = entry
                if kind == "func":
                    self._check_sig(node, obj, f"{func.id}()")
                elif kind == "class":
                    ci: ClassInfo = obj  # type: ignore[assignment]
                    init = ci.methods.get("__init__")
                    # dataclasses synthesize __init__; bases may define
                    # it — only check an explicit local __init__.
                    if init is not None and not ci.bases:
                        self._check_sig(node, init, f"{ci.name}()")
        elif isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and self.current_class is not None
            ):
                sig = self.current_class.methods.get(func.attr)
                # Inherited methods not indexed: only check when the
                # class has no bases or defines the method itself.
                if sig is not None:
                    self._check_sig(node, sig, f"self.{func.attr}()")
            elif isinstance(func.value, ast.Name):
                entry = self.resolved.get(func.value.id)
                if entry and entry[0] == "module":
                    minfo: ModuleInfo = entry[1]  # type: ignore
                    sig = minfo.functions.get(func.attr)
                    if sig is not None:
                        self._check_sig(
                            node,
                            sig,
                            f"{minfo.modname}.{func.attr}()",
                        )
        self.generic_visit(node)


def _run_shared_pass(ctx: Context) -> None:
    """Walk every module once, whichever of the three rules asked."""
    if getattr(ctx, "_typecheck_ran", False):
        return
    ctx._typecheck_ran = True  # type: ignore[attr-defined]
    for info in ctx.index.values():
        _Checker(info, ctx).visit(info.tree)


_IMPORT_FIXTURE = {
    "pkg/good.py": "def takes_two(a, b, *, c=0):\n    return a\n",
    "pkg/bad.py": (
        "from pkg.good import takes_two, absent\n"
        "from pkg import good\n"
        "x = good.nothing_here\n"
        "takes_two(1)\n"
    ),
}


@register
class ImportRule(Rule):
    id = "GL-IMPORT"
    title = "from-imports must name bindings that exist"
    rationale = (
        "A bad from-import raises at import time only on the paths that "
        "reach it; lazily imported modules hide it until a TPU run."
    )
    fixtures = _IMPORT_FIXTURE

    def check(self, ctx: Context) -> None:
        _run_shared_pass(ctx)


@register
class AttrRule(Rule):
    id = "GL-ATTR"
    title = "module attribute access must name bindings that exist"
    rationale = (
        "mod.NAME on a package module object fails only when executed; "
        "rarely-taken branches (fault paths) ship the AttributeError."
    )
    fixtures = _IMPORT_FIXTURE

    def check(self, ctx: Context) -> None:
        _run_shared_pass(ctx)


@register
class ArityRule(Rule):
    id = "GL-ARITY"
    title = "statically resolvable calls must match the signature"
    rationale = (
        "Wrong arity / unknown keywords on package-internal calls are "
        "runtime TypeErrors on exactly the branches tests miss."
    )
    fixtures = _IMPORT_FIXTURE

    def check(self, ctx: Context) -> None:
        _run_shared_pass(ctx)
