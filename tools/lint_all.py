"""Unified lint/QA runner with ONE exit code — the preflight gate
tpu_session.sh runs before burning a TPU window on the ladder.

Stages (each prints its own verdict; the runner exits nonzero if ANY
stage failed):

1. **graftlint** — the full rule set over the repo (tools/graftlint),
   plus its self-test (every registered rule must fire on its fixture:
   a silently dead rule is worse than no rule).
2. **mutmut-config sanity** — the mutation-skip config both mutmut and
   tools/mutation_run.py consume must stay importable and structurally
   sound (non-empty marker tuples, tests + graftlint fixtures excluded
   from mutation targets).
2b. **journal schema self-check** — the crash-safe round journal's
   record schema (debate/journal.py RECORD_FIELDS): every record type
   has a validating example and the validator provably fires on broken
   records — a resume that silently misreads its journal is a lost
   round.
3. **bench-trend** (``--full`` only) — every committed BENCH_*.json
   must schema-validate and join into the perf-trajectory table
   (tools/bench_trend.py): a malformed bench file fails the gate
   instead of silently dropping out of the record.
4. **unroll compile check** (``--full`` only — it jit-compiles an
   80-layer config three times, minutes of CPU) — the decode-scan
   unroll cost measurement, tools/unroll_compile_check.py.

Usage:
    python tools/lint_all.py          # graftlint + mutmut sanity
    python tools/lint_all.py --full   # + bench trend + unroll check
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _stage_graftlint() -> bool:
    from tools.graftlint import core

    failures = core.self_test()
    for f in failures:
        print(f"lint_all: graftlint self-test: {f}", file=sys.stderr)
    try:
        result = core.run()
    except (SyntaxError, ValueError) as e:
        print(f"lint_all: graftlint: {e}", file=sys.stderr)
        print("lint_all: graftlint FAILED", file=sys.stderr)
        return False
    for finding in result.findings:
        print(finding.render())
    ok = not failures and result.exit_code == 0
    print(
        f"lint_all: graftlint {'OK' if ok else 'FAILED'} "
        f"({len(result.findings)} finding(s), "
        f"{len(failures)} dead rule(s), {result.n_files} files)",
        file=sys.stderr,
    )
    return ok


def _stage_mutmut_sanity() -> bool:
    ok = True

    def fail(msg: str) -> None:
        nonlocal ok
        ok = False
        print(f"lint_all: mutmut-config: {msg}", file=sys.stderr)

    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "mutmut_config", REPO / "mutmut_config.py"
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    except Exception as e:
        fail(f"import failed: {e}")
        print("lint_all: mutmut-config FAILED", file=sys.stderr)
        return False
    for name in ("_SKIP_LINE_MARKERS", "_SKIP_PATH_FRAGMENTS"):
        val = getattr(module, name, None)
        if not (
            isinstance(val, tuple)
            and val
            and all(isinstance(m, str) and m for m in val)
        ):
            fail(f"{name} must be a non-empty tuple of strings")
    if not callable(getattr(module, "pre_mutation", None)):
        fail("pre_mutation hook missing")
    frags = getattr(module, "_SKIP_PATH_FRAGMENTS", ())
    for required in ("/tests/", "/tools/graftlint/"):
        if required not in frags:
            fail(f"_SKIP_PATH_FRAGMENTS must exclude {required!r}")
    # mutation_run must agree (it imports the same markers by path) and
    # must never target the self-test fixture package.
    from tools.mutation_run import DEFAULT_TARGETS, SKIP_LINE_MARKERS

    if SKIP_LINE_MARKERS != module._SKIP_LINE_MARKERS:
        fail("mutation_run.SKIP_LINE_MARKERS diverged from mutmut_config")
    for target in DEFAULT_TARGETS:
        if "tools/graftlint" in target:
            fail(f"graftlint fixtures are a mutation target: {target}")
    print(
        f"lint_all: mutmut-config {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def _stage_journal_schema() -> bool:
    try:
        from adversarial_spec_tpu.debate import journal
    except Exception as e:
        print(f"lint_all: journal-schema: import failed: {e}", file=sys.stderr)
        print("lint_all: journal-schema FAILED", file=sys.stderr)
        return False
    problems = journal.self_check()
    for p in problems:
        print(f"lint_all: journal-schema: {p}", file=sys.stderr)
    ok = not problems
    print(
        f"lint_all: journal-schema {'OK' if ok else 'FAILED'} "
        f"({len(journal.RECORD_TYPES)} record type(s))",
        file=sys.stderr,
    )
    return ok


def _stage_bench_trend() -> bool:
    from tools.bench_trend import collect

    rows, problems = collect(REPO)
    for p in problems:
        print(f"lint_all: bench-trend: {p}", file=sys.stderr)
    ok = not problems and bool(rows)
    if not rows:
        print("lint_all: bench-trend: no BENCH_*.json found", file=sys.stderr)
    print(
        f"lint_all: bench-trend {'OK' if ok else 'FAILED'} "
        f"({len(rows)} bench file(s))",
        file=sys.stderr,
    )
    return ok


def _stage_unroll() -> bool:
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "unroll_compile_check.py")],
        cwd=REPO,
    )
    ok = r.returncode == 0
    print(
        f"lint_all: unroll-compile-check {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--full",
        action="store_true",
        help="also run the (slow) unroll compile check",
    )
    args = ap.parse_args(argv)
    ok = _stage_graftlint()
    ok = _stage_mutmut_sanity() and ok
    ok = _stage_journal_schema() and ok
    if args.full:
        ok = _stage_bench_trend() and ok
        ok = _stage_unroll() and ok
    print(
        f"lint_all: {'ALL OK' if ok else 'FAILURES'}",
        file=sys.stderr,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
