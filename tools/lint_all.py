"""Unified lint/QA runner with ONE exit code — the preflight gate
tpu_session.sh runs before burning a TPU window on the ladder.

Stages (each prints its own verdict; the runner exits nonzero if ANY
stage failed):

1. **graftlint** — the full rule set over the repo (tools/graftlint),
   plus its self-test (every registered rule must fire on its fixture:
   a silently dead rule is worse than no rule).
2. **mutmut-config sanity** — the mutation-skip config both mutmut and
   tools/mutation_run.py consume must stay importable and structurally
   sound (non-empty marker tuples, tests + graftlint fixtures excluded
   from mutation targets).
2b. **journal schema self-check** — the crash-safe round journal's
   record schema (debate/journal.py RECORD_FIELDS): every record type
   has a validating example and the validator provably fires on broken
   records — a resume that silently misreads its journal is a lost
   round.
3. **bench-trend** (``--full`` only) — every committed BENCH_*.json
   must schema-validate and join into the perf-trajectory table
   (tools/bench_trend.py): a malformed bench file fails the gate
   instead of silently dropping out of the record.
4. **replay-smoke** (``--full`` only) — a tiny seeded
   tools/load_replay.py sweep on the mock daemon must emit a
   BENCH_capacity.json payload that bench_trend's capacity schema
   accepts (>=2 knob arms, numeric frontier): the load harness and
   the capacity gate can never drift apart unnoticed.
5. **unroll compile check** (``--full`` only — it jit-compiles an
   80-layer config three times, minutes of CPU) — the decode-scan
   unroll cost measurement, tools/unroll_compile_check.py.

Usage:
    python tools/lint_all.py            # graftlint + mutmut sanity
    python tools/lint_all.py --changed  # lint only files changed vs main
    python tools/lint_all.py --full     # + bench trend + unroll check
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def lintable(names: list[str], repo: Path = REPO) -> list[str]:
    """Repo-relative names filtered to existing .py files under the
    lint roots (pure — the testable half of --changed)."""
    from tools.graftlint.core import DEFAULT_ROOTS

    roots = tuple(
        r if r.endswith(".py") else r + "/" for r in DEFAULT_ROOTS
    )
    out = []
    for name in names:
        if not name.endswith(".py"):
            continue
        if not any(name == r or name.startswith(r) for r in roots):
            continue
        if (repo / name).is_file():
            out.append(name)
    return sorted(set(out))


def changed_py_files(repo: Path = REPO, base: str = "main") -> list[str] | None:
    """Lintable files changed vs ``base`` (committed + worktree +
    untracked); None when git cannot answer (fall back to a full lint)."""
    names: list[str] = []
    try:
        for args in (
            ["git", "diff", "--name-only", base],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            r = subprocess.run(
                args, cwd=repo, capture_output=True, text=True, timeout=30
            )
            if r.returncode != 0:
                return None
            names += r.stdout.splitlines()
    except (OSError, subprocess.SubprocessError):
        return None
    return lintable(names, repo)


def _stage_graftlint(paths: list[str] | None = None) -> bool:
    from tools.graftlint import core

    failures = core.self_test()
    for f in failures:
        print(f"lint_all: graftlint self-test: {f}", file=sys.stderr)
    if paths is not None and not paths:
        # --changed with nothing changed: the self-test above is the
        # whole lint stage.
        ok = not failures
        print(
            f"lint_all: graftlint {'OK' if ok else 'FAILED'} "
            "(0 changed files)",
            file=sys.stderr,
        )
        return ok
    try:
        result = core.run(
            [str(REPO / p) for p in paths] if paths else None
        )
    except (SyntaxError, ValueError) as e:
        print(f"lint_all: graftlint: {e}", file=sys.stderr)
        print("lint_all: graftlint FAILED", file=sys.stderr)
        return False
    for finding in result.findings:
        print(finding.render())
    ok = not failures and result.exit_code == 0
    slowest = sorted(
        result.rule_seconds.items(), key=lambda kv: -kv[1]
    )[:3]
    timing = ", ".join(f"{r} {s:.2f}s" for r, s in slowest)
    print(
        f"lint_all: graftlint {'OK' if ok else 'FAILED'} "
        f"({len(result.findings)} finding(s), "
        f"{len(failures)} dead rule(s), {result.n_files} files; "
        f"slowest rules: {timing})",
        file=sys.stderr,
    )
    return ok


def _stage_graftlint_config() -> bool:
    """THE pyproject-vs-code-defaults drift guard (hoisted here from
    per-module test pins): the [tool.graftlint] table and the in-code
    defaults must be the same config — the defaults exist so fixture
    trees lint without a pyproject, not as a second opinion."""
    from tools.graftlint.config import config_drift

    try:
        drift = config_drift(REPO)
    except ValueError as e:
        print(f"lint_all: graftlint-config: {e}", file=sys.stderr)
        drift = ["<unreadable table>"]
    for d in drift:
        print(f"lint_all: graftlint-config: drift: {d}", file=sys.stderr)
    ok = not drift
    print(
        f"lint_all: graftlint-config {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def _stage_lockdep_selftest() -> bool:
    """Prove the runtime lockdep sanitizer is live, mirroring graftlint
    ``--self-test``: a synthetic two-lock inversion must be detected
    and must name both stacks. A sanitizer that silently stopped
    detecting would make every 'zero violations' green a lie."""
    from adversarial_spec_tpu.resilience import lockdep

    problems = lockdep.self_test()
    for p in problems:
        print(f"lint_all: lockdep-selftest: {p}", file=sys.stderr)
    ok = not problems
    print(
        f"lint_all: lockdep-selftest {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def _stage_mutmut_sanity() -> bool:
    ok = True

    def fail(msg: str) -> None:
        nonlocal ok
        ok = False
        print(f"lint_all: mutmut-config: {msg}", file=sys.stderr)

    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "mutmut_config", REPO / "mutmut_config.py"
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    except Exception as e:
        fail(f"import failed: {e}")
        print("lint_all: mutmut-config FAILED", file=sys.stderr)
        return False
    for name in ("_SKIP_LINE_MARKERS", "_SKIP_PATH_FRAGMENTS"):
        val = getattr(module, name, None)
        if not (
            isinstance(val, tuple)
            and val
            and all(isinstance(m, str) and m for m in val)
        ):
            fail(f"{name} must be a non-empty tuple of strings")
    if not callable(getattr(module, "pre_mutation", None)):
        fail("pre_mutation hook missing")
    frags = getattr(module, "_SKIP_PATH_FRAGMENTS", ())
    for required in ("/tests/", "/tools/graftlint/"):
        if required not in frags:
            fail(f"_SKIP_PATH_FRAGMENTS must exclude {required!r}")
    # mutation_run must agree (it imports the same markers by path) and
    # must never target the self-test fixture package.
    from tools.mutation_run import DEFAULT_TARGETS, SKIP_LINE_MARKERS

    if SKIP_LINE_MARKERS != module._SKIP_LINE_MARKERS:
        fail("mutation_run.SKIP_LINE_MARKERS diverged from mutmut_config")
    for target in DEFAULT_TARGETS:
        if "tools/graftlint" in target:
            fail(f"graftlint fixtures are a mutation target: {target}")
    print(
        f"lint_all: mutmut-config {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def _stage_journal_schema() -> bool:
    try:
        from adversarial_spec_tpu.debate import journal
    except Exception as e:
        print(f"lint_all: journal-schema: import failed: {e}", file=sys.stderr)
        print("lint_all: journal-schema FAILED", file=sys.stderr)
        return False
    problems = journal.self_check()
    for p in problems:
        print(f"lint_all: journal-schema: {p}", file=sys.stderr)
    ok = not problems
    print(
        f"lint_all: journal-schema {'OK' if ok else 'FAILED'} "
        f"({len(journal.RECORD_TYPES)} record type(s))",
        file=sys.stderr,
    )
    return ok


def _stage_bench_trend() -> bool:
    from tools.bench_trend import collect

    rows, problems = collect(REPO)
    for p in problems:
        print(f"lint_all: bench-trend: {p}", file=sys.stderr)
    ok = not problems and bool(rows)
    if not rows:
        print("lint_all: bench-trend: no BENCH_*.json found", file=sys.stderr)
    print(
        f"lint_all: bench-trend {'OK' if ok else 'FAILED'} "
        f"({len(rows)} bench file(s))",
        file=sys.stderr,
    )
    return ok


def _stage_replay_smoke() -> bool:
    """A tiny seeded load_replay sweep must produce a schema-valid
    capacity payload (tools/bench_trend.py's capacity contract) — the
    replay harness and the frontier gate can never drift apart
    unnoticed."""
    import json
    import tempfile

    from tools.bench_trend import validate_bench_file

    ok = True
    with tempfile.TemporaryDirectory(prefix="advspec-replay-smoke-") as td:
        out = Path(td) / "BENCH_capacity.json"
        r = subprocess.run(
            [
                sys.executable,
                str(REPO / "tools" / "load_replay.py"),
                "--smoke",
                "--bench-out",
                str(out),
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if r.returncode != 0 or not out.is_file():
            print(
                f"lint_all: replay-smoke: load_replay exited "
                f"{r.returncode}: {r.stderr[-400:]}",
                file=sys.stderr,
            )
            ok = False
        else:
            row, problems = validate_bench_file(out)
            for p in problems:
                print(f"lint_all: replay-smoke: {p}", file=sys.stderr)
            payload = json.loads(out.read_text(encoding="utf-8"))
            arms = payload.get("frontier", {})
            if len(arms) < 2:
                print(
                    f"lint_all: replay-smoke: expected >=2 knob arms, "
                    f"got {len(arms)}",
                    file=sys.stderr,
                )
                ok = False
            ok = ok and not problems and row is not None
    print(
        f"lint_all: replay-smoke {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def _stage_unroll() -> bool:
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "unroll_compile_check.py")],
        cwd=REPO,
    )
    ok = r.returncode == 0
    print(
        f"lint_all: unroll-compile-check {'OK' if ok else 'FAILED'}",
        file=sys.stderr,
    )
    return ok


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--full",
        action="store_true",
        help="also run the (slow) unroll compile check",
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="lint only files changed vs --base (the tpu_session.sh "
        "fast preflight); falls back to a full lint when git cannot "
        "answer. Absence-proving checks (GL-CONFIG) skip on a subset.",
    )
    ap.add_argument(
        "--base",
        default="main",
        help="base ref for --changed (default: main)",
    )
    args = ap.parse_args(argv)
    paths: list[str] | None = None
    if args.changed:
        paths = changed_py_files(REPO, args.base)
        if paths is None:
            print(
                "lint_all: --changed: git unavailable, full lint",
                file=sys.stderr,
            )
        elif not paths:
            print(
                f"lint_all: --changed: no lintable files changed vs "
                f"{args.base}; graftlint self-test + config stages only",
                file=sys.stderr,
            )
        else:
            print(
                f"lint_all: --changed: {len(paths)} file(s) vs "
                f"{args.base}",
                file=sys.stderr,
            )
    ok = _stage_graftlint(paths)
    ok = _stage_graftlint_config() and ok
    ok = _stage_lockdep_selftest() and ok
    ok = _stage_mutmut_sanity() and ok
    ok = _stage_journal_schema() and ok
    if args.full:
        ok = _stage_bench_trend() and ok
        ok = _stage_replay_smoke() and ok
        ok = _stage_unroll() and ok
    print(
        f"lint_all: {'ALL OK' if ok else 'FAILURES'}",
        file=sys.stderr,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
