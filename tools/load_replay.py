"""Trace-replay load harness + capacity-frontier sweep for ``advspec
serve`` (ROADMAP item 3 — the number behind "millions of users").

Three layers, each usable alone:

1. **Trace acquisition** — either synthesize a heavy-tailed
   multi-tenant arrival trace from a seeded spec (lognormal
   inter-arrivals, Zipf tenant skew, mixed interactive/batch tiers,
   lognormal prompt shapes), or reconstruct one from a flight-recorder
   JSONL dump (``--events-out`` / ``obs.dump_events``) recorded with
   ``ADVSPEC_OBS_ARRIVALS=1``. The reader follows the journal
   tolerant-reader discipline: a torn final line is discarded, a
   foreign or invalid line is skipped ALONE — one bad byte never
   poisons the rest of a recording.

2. **Open-loop replay** — drive an in-process serve daemon over the
   unix socket (``serve/client.py``) with schedule-faithful arrivals
   at k× the recorded rate: each submit fires at its scheduled
   offset whether or not the server has kept up (a slow server must
   never slow the arrival process — that is what "open loop" means,
   and what closed-loop harnesses get wrong about overload). Measures
   p50/p95/p99 TTFT, round latency, shed fraction, and brownout
   occupancy (sampled via the stats op's ``pressure`` snapshot from a
   second connection).

3. **Frontier sweep** — binary-search k until the configured SLO
   breaches; the highest non-breaching accepted-debates/s per knob arm
   is the CAPACITY FRONTIER, written as a BENCH-style payload
   (``BENCH_capacity.json``) that ``tools/bench_trend.py`` schema-
   enforces (``_CAPACITY_REQUIRED``) — a >10% frontier drop vs the
   committed value fails the gate like any other perf regression.

Round-trip property (the replay-fidelity pin): requests use a
CANONICAL SHAPE ENCODING — fixed 2-opponent mock pool, fixed per-tier
decode budget, spec length a multiple of 4 rendered by
``canonical_spec`` — chosen so the admission estimate
(``driver.estimate_debate_tokens``) is INVERTIBLE: a recorded serve
event's ``(tokens, tier)`` reconstructs the exact spec text, so
record → reconstruct → replay at 1× reproduces byte-identical
transcripts on the deterministic mock engine.

Usage:
    python tools/load_replay.py --smoke                # tiny seeded sweep
    python tools/load_replay.py --rate 2.0 --json      # one run at 2x
    python tools/load_replay.py --replay events.jsonl  # recorded trace
    python tools/load_replay.py --sweep --bench-out BENCH_capacity.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import random
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from adversarial_spec_tpu import obs as obs_mod  # noqa: E402
from adversarial_spec_tpu import serve as serve_mod  # noqa: E402
from adversarial_spec_tpu.obs.metrics import percentile  # noqa: E402
from adversarial_spec_tpu.serve import driver  # noqa: E402
from adversarial_spec_tpu.serve.client import ServeClient  # noqa: E402
from adversarial_spec_tpu.serve.daemon import ServeDaemon  # noqa: E402

# -- canonical shape encoding ---------------------------------------------
#
# Everything below is FIXED so the admission estimate is an injective
# function of (spec_chars, tier) and therefore invertible from a
# recorded serve event. Changing any constant breaks replay of older
# recordings — version the recording format before touching these.

MODELS = ("mock://critic?v=0", "mock://critic?v=1")
TIER_MAX_NEW = {"interactive": 96, "batch": 384}
MIN_SPEC_CHARS = 128
MAX_SPEC_CHARS = 4096

_SPEC_HEADER = (
    "## Goals\nServe heavy replayed traffic within the SLO.\n"
    "## Constraints\n"
)
_SPEC_FILLER = "The daemon SHALL shed typed, never collapse. "


def canonical_spec(spec_chars: int) -> str:
    """The deterministic spec text of EXACTLY ``spec_chars`` characters
    (clamped to the canonical range, rounded down to a multiple of 4 so
    the 4-chars-per-token estimate divides evenly)."""
    n = max(MIN_SPEC_CHARS, min(int(spec_chars), MAX_SPEC_CHARS))
    n -= n % 4
    body = _SPEC_HEADER + _SPEC_FILLER * (
        1 + max(0, n - len(_SPEC_HEADER)) // len(_SPEC_FILLER)
    )
    return body[:n]


def est_tokens_for(spec_chars: int, tier: str) -> int:
    """The admission estimate the daemon will compute for a canonical
    request — via the REAL estimator, never a reimplementation."""
    return driver.estimate_debate_tokens(
        {
            "spec": canonical_spec(spec_chars),
            "models": list(MODELS),
            "max_new_tokens": TIER_MAX_NEW[tier],
        }
    )


def spec_chars_from_est(est_tokens: int, tier: str) -> int | None:
    """Invert ``est_tokens_for``: recorded estimate + tier → canonical
    spec length. None when the estimate cannot come from a canonical
    request (foreign recording) — the tolerant reader skips those."""
    if est_tokens % len(MODELS):
        return None
    per_opp = est_tokens // len(MODELS)
    spec_tokens = per_opp - 256 - TIER_MAX_NEW.get(tier, 0)
    if tier not in TIER_MAX_NEW or spec_tokens < MIN_SPEC_CHARS // 4:
        return None
    chars = spec_tokens * 4
    if chars > MAX_SPEC_CHARS:
        return None
    return chars


@dataclass
class ReplayRequest:
    """One scheduled arrival: WHEN (offset from trace start), WHO
    (tenant/tier), and HOW BIG (canonical spec length)."""

    arrival_s: float
    tenant: str
    tier: str
    spec_chars: int

    @property
    def spec(self) -> str:
        return canonical_spec(self.spec_chars)

    @property
    def max_new_tokens(self) -> int:
        return TIER_MAX_NEW[self.tier]


# -- trace synthesis -------------------------------------------------------


@dataclass
class SynthSpec:
    """Seeded generator spec for a heavy-tailed multi-tenant trace.

    Defaults model the mixed corpus the matched-ceiling scouting paper
    motivates: bursty lognormal inter-arrivals (sigma 1.0 → heavy
    tail), Zipf-skewed tenants (one hot tenant, a long cold tail), a
    batch minority, and lognormal prompt sizes."""

    seed: int = 0
    requests: int = 64
    tenants: int = 4
    zipf_s: float = 1.2
    mean_interarrival_s: float = 0.02
    interarrival_sigma: float = 1.0
    batch_fraction: float = 0.25
    mean_spec_chars: float = 512.0
    spec_sigma: float = 0.6


def synthesize(spec: SynthSpec) -> list[ReplayRequest]:
    """Deterministic trace from a seed: same spec → same requests,
    byte for byte (the seed-determinism pin)."""
    rng = random.Random(spec.seed)
    weights = [1.0 / (r + 1) ** spec.zipf_s for r in range(spec.tenants)]
    # lognormal with mean spec.mean_interarrival_s: mu shifts so the
    # heavy tail does not also inflate the average offered rate.
    mu = math.log(spec.mean_interarrival_s) - spec.interarrival_sigma**2 / 2
    smu = math.log(spec.mean_spec_chars) - spec.spec_sigma**2 / 2
    out: list[ReplayRequest] = []
    t = 0.0
    for _ in range(spec.requests):
        t += rng.lognormvariate(mu, spec.interarrival_sigma)
        tenant = rng.choices(range(spec.tenants), weights=weights)[0]
        tier = "batch" if rng.random() < spec.batch_fraction else "interactive"
        chars = int(rng.lognormvariate(smu, spec.spec_sigma))
        out.append(
            ReplayRequest(
                arrival_s=round(t, 6),
                tenant=f"t{tenant}",
                tier=tier,
                # canonical_spec clamps + rounds; store the canonical
                # value so est inversion round-trips exactly.
                spec_chars=len(canonical_spec(chars)),
            )
        )
    return out


# -- trace reconstruction (tolerant reader) --------------------------------


def read_recording(path: str | Path) -> tuple[list[ReplayRequest], dict]:
    """Reconstruct the arrival trace from a flight-recorder JSONL dump.

    Journal tolerant-reader discipline (debate/journal.py): a torn
    final line (no trailing newline — a crashed writer) is discarded;
    a line that fails to parse, has a foreign event type, or carries a
    non-canonical shape is skipped ALONE and counted, never fatal.
    Only serve ``accepted``/``shed`` events with a positive
    ``arrival_s`` enter the trace (those are the admission edges the
    daemon stamps when ``ADVSPEC_OBS_ARRIVALS=1``).

    Returns (requests sorted by arrival, reader report)."""
    raw = Path(path).read_bytes()
    lines = raw.split(b"\n")
    torn = 0
    if lines and lines[-1] == b"":
        lines.pop()
    elif lines:
        lines.pop()  # torn tail: incomplete write, discard
        torn = 1
    reqs: list[ReplayRequest] = []
    skipped = 0
    for line in lines:
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            skipped += 1
            continue
        if not isinstance(obj, dict) or obj.get("type") != "serve":
            continue  # foreign/other event types: not ours to judge
        if obj.get("op") not in ("accepted", "shed"):
            continue
        arrival = obj.get("arrival_s")
        if not isinstance(arrival, (int, float)) or arrival <= 0:
            continue  # unarmed recording (or pre-arrival version)
        tier = obj.get("tier", "")
        tokens = obj.get("tokens", 0)
        if not isinstance(tokens, int):
            skipped += 1
            continue
        chars = spec_chars_from_est(tokens, tier)
        if chars is None:
            skipped += 1  # non-canonical shape: foreign workload
            continue
        reqs.append(
            ReplayRequest(
                arrival_s=float(arrival),
                tenant=str(obj.get("tenant", "t0")),
                tier=tier,
                spec_chars=chars,
            )
        )
    reqs.sort(key=lambda r: r.arrival_s)
    if reqs:
        base = reqs[0].arrival_s  # re-base: first arrival = t0
        for r in reqs:
            r.arrival_s = round(r.arrival_s - base, 6)
    report = {"requests": len(reqs), "skipped": skipped, "torn_tail": torn}
    return reqs, report


def tenant_rates(reqs: list[ReplayRequest]) -> dict[str, float]:
    """Per-tenant mean arrival rate (requests/s) over the trace span —
    the summary line obs_dump prints for armed recordings."""
    if not reqs:
        return {}
    span = max(r.arrival_s for r in reqs) - min(r.arrival_s for r in reqs)
    span = max(span, 1e-6)
    counts: dict[str, int] = {}
    for r in reqs:
        counts[r.tenant] = counts.get(r.tenant, 0) + 1
    return {t: round(c / span, 3) for t, c in sorted(counts.items())}


# -- open-loop replay ------------------------------------------------------


@dataclass
class SLOSpec:
    """The breach condition the frontier is defined against."""

    ttft_p95_s: float = 0.5
    max_shed_fraction: float = 0.02


@dataclass
class ServeKnobs:
    """The admission-side knob arm under sweep. ``replicas`` scales the
    backlog cap through the scheduler's capacity provider — the same
    mechanism the elastic fleet uses, so "replica count 1 vs 3" is an
    honest single-process stand-in for a fleet arm."""

    replicas: int = 1
    max_queue_depth: int = 8
    max_backlog_tokens: int = 24_000
    label: str = ""

    def name(self) -> str:
        return self.label or f"replicas={self.replicas}"


@dataclass
class RunResult:
    metrics: dict = field(default_factory=dict)
    transcripts: list = field(default_factory=list)


class _PressurePoller(threading.Thread):
    """Samples the stats op's ``pressure`` snapshot on a SECOND
    connection while the storm runs — brownout occupancy is the
    fraction of samples with brownout set (the wire-level view the
    stats-op fix exposes)."""

    def __init__(self, sock: str, interval_s: float = 0.025) -> None:
        super().__init__(daemon=True)
        self._sock = sock
        self._interval = interval_s
        self._halt = threading.Event()
        self.samples: list[dict] = []

    def run(self) -> None:
        try:
            client = ServeClient(self._sock, timeout_s=5)
        except OSError:
            return
        try:
            while not self._halt.is_set():
                try:
                    ev = client.stats()
                except (OSError, TimeoutError, ConnectionError):
                    return
                p = ev.get("pressure")
                if isinstance(p, dict):
                    self.samples.append(p)
                self._halt.wait(self._interval)
        finally:
            client.close()

    def stop(self) -> dict:
        self._halt.set()
        self.join(timeout=2)
        n = len(self.samples)
        if not n:
            return {"samples": 0, "brownout_occupancy": 0.0,
                    "peak_backlog_tokens": 0}
        return {
            "samples": n,
            "brownout_occupancy": round(
                sum(1 for s in self.samples if s.get("brownout")) / n, 4
            ),
            "peak_backlog_tokens": max(
                int(s.get("backlog_tokens", 0)) for s in self.samples
            ),
        }


def replay_once(
    reqs: list[ReplayRequest],
    rate: float,
    *,
    knobs: ServeKnobs | None = None,
    collect_transcripts: bool = False,
    events_out: str | None = None,
    poll_pressure: bool = True,
    collect_timeout_s: float = 120.0,
) -> RunResult:
    """One open-loop replay run against a fresh in-process daemon.

    Arrivals are SCHEDULE-FAITHFUL: request i fires at
    ``t0 + arrival_s/rate`` via a non-blocking submit; a server that
    falls behind accumulates backlog (and sheds) instead of slowing
    the arrival process. ``schedule_lateness_p99_s`` in the result is
    the fidelity check — how far behind its schedule the GENERATOR
    ran (socket-buffer pushback only, normally sub-millisecond).
    """
    knobs = knobs or ServeKnobs()
    rate = max(float(rate), 1e-6)
    old = serve_mod.config()
    old_cfg = {
        "max_queue_depth": old.max_queue_depth,
        "max_backlog_tokens": old.max_backlog_tokens,
        "tenant_quota_tokens": old.tenant_quota_tokens,
        "drain_deadline_s": old.drain_deadline_s,
    }
    serve_mod.reset_stats()
    serve_mod.configure(
        max_queue_depth=knobs.max_queue_depth,
        max_backlog_tokens=knobs.max_backlog_tokens,
        tenant_quota_tokens=0,
        drain_deadline_s=10.0,
    )
    if events_out:
        # Arm arrivals + a ring large enough for the whole run; the
        # reset re-bases the arrival epoch so offsets start near 0.
        obs_mod.configure(enabled=True, arrivals=True, recorder_size=65536)
        obs_mod.reset_stats()
    result = RunResult()
    try:
        with tempfile.TemporaryDirectory(prefix="advspec-replay-") as td:
            sock = os.path.join(td, "serve.sock")
            ready = threading.Event()
            daemon = ServeDaemon(sock, sessions_dir=os.path.join(td, "s"))
            if knobs.replicas > 1:
                daemon.sched.set_capacity_provider(lambda: knobs.replicas)
            th = threading.Thread(
                target=lambda: asyncio.run(daemon.run(ready=ready)),
                daemon=True,
            )
            th.start()
            if not ready.wait(10):
                raise RuntimeError("replay daemon did not come up")
            poller = None
            if poll_pressure:
                poller = _PressurePoller(sock)
                poller.start()
            client = ServeClient(sock, timeout_s=collect_timeout_s)
            try:
                lateness: list[float] = []
                submitted: list[tuple[str, ReplayRequest]] = []
                t0 = time.monotonic()
                for r in reqs:
                    target = t0 + r.arrival_s / rate
                    delay = target - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    rid = client.submit_debate(
                        r.spec,
                        list(MODELS),
                        tenant=r.tenant,
                        tier=r.tier,
                        stream=False,
                        max_new_tokens=r.max_new_tokens,
                    )
                    lateness.append(max(0.0, time.monotonic() - target))
                    submitted.append((rid, r))
                # Collect AFTER the full schedule has fired (open loop:
                # reads never gate writes).
                ttfts: list[float] = []
                rounds: list[float] = []
                accepted = completed = shed = lost = 0
                shed_reasons: dict[str, int] = {}
                for rid, r in submitted:
                    evs = client.collect(rid, timeout_s=collect_timeout_s)
                    last = evs[-1]
                    if evs[0]["event"] == "accepted":
                        accepted += 1
                        opp_errors = [
                            x["error"]
                            for x in last.get("results", [])
                            if x.get("error")
                        ]
                        if (
                            last["event"] != "result"
                            or last.get("error")
                            or opp_errors
                        ):
                            lost += 1
                            if collect_transcripts:
                                result.transcripts.append(None)
                            continue
                        completed += 1
                        ttfts.append(float(last["ttft_s"]))
                        rounds.append(float(last["wall_s"]))
                        if collect_transcripts:
                            result.transcripts.append(
                                [x["response"] for x in last["results"]]
                            )
                    elif last["event"] == "shed":
                        shed += 1
                        reason = last.get("reason", "?")
                        shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
                        if collect_transcripts:
                            result.transcripts.append(None)
                    else:
                        lost += 1
                        if collect_transcripts:
                            result.transcripts.append(None)
                wall = max(time.monotonic() - t0, 1e-6)
                pressure = poller.stop() if poller else {"samples": 0}
                client.drain()
            finally:
                client.close()
                if poller:
                    poller.stop()
                th.join(timeout=15)
            if events_out:
                obs_mod.dump_events(events_out)
        total = max(len(submitted), 1)
        result.metrics = {
            "arm": knobs.name(),
            "rate_multiplier": round(rate, 4),
            "offered": len(submitted),
            "offered_per_s": round(len(submitted) / wall, 3),
            "accepted": accepted,
            "completed": completed,
            "shed": shed,
            "lost": lost,
            "shed_reasons": shed_reasons,
            "shed_fraction": round(shed / total, 4),
            "debates_per_s": round(completed / wall, 3),
            "wall_s": round(wall, 3),
            "ttft_p50_s": round(percentile(ttfts, 0.5), 6),
            "ttft_p95_s": round(percentile(ttfts, 0.95), 6),
            "ttft_p99_s": round(percentile(ttfts, 0.99), 6),
            "round_p50_s": round(percentile(rounds, 0.5), 6),
            "round_p95_s": round(percentile(rounds, 0.95), 6),
            "round_p99_s": round(percentile(rounds, 0.99), 6),
            "schedule_lateness_p99_s": round(
                percentile(lateness, 0.99), 6
            ),
            "pressure": pressure,
        }
        return result
    finally:
        serve_mod.configure(**old_cfg)


def slo_breaches(metrics: dict, slo: SLOSpec) -> list[str]:
    """Typed breach list (empty = within SLO). Lost accepted work is
    ALWAYS a breach — a frontier that drops requests is not capacity."""
    out = []
    if metrics.get("lost"):
        out.append(f"lost {metrics['lost']} accepted request(s)")
    if metrics.get("ttft_p95_s", 0.0) > slo.ttft_p95_s:
        out.append(
            f"ttft_p95 {metrics['ttft_p95_s']:.4f}s > {slo.ttft_p95_s}s"
        )
    if metrics.get("shed_fraction", 0.0) > slo.max_shed_fraction:
        out.append(
            f"shed_fraction {metrics['shed_fraction']:.4f} > "
            f"{slo.max_shed_fraction}"
        )
    return out


# -- frontier sweep --------------------------------------------------------


def sweep_arm(
    reqs: list[ReplayRequest],
    knobs: ServeKnobs,
    slo: SLOSpec,
    *,
    k_start: float = 1.0,
    max_doublings: int = 4,
    bisect_iters: int = 2,
    log=lambda m: None,
) -> dict:
    """Binary-search the rate multiplier k for one knob arm: double
    from ``k_start`` until the SLO breaches (or the doubling budget
    runs out — reported as an UNBREACHED frontier, a lower bound),
    then bisect. The frontier is the measured accepted-debates/s of
    the highest non-breaching run."""

    def probe(k: float) -> tuple[dict, list[str]]:
        m = replay_once(reqs, k, knobs=knobs).metrics
        b = slo_breaches(m, slo)
        log(
            f"  {knobs.name()} k={k:g}: {m['debates_per_s']} deb/s, "
            f"ttft_p95={m['ttft_p95_s']}s, shed={m['shed_fraction']}"
            + (f" BREACH ({'; '.join(b)})" if b else "")
        )
        return m, b

    probes = 0
    good_k, good_m = 0.0, None
    bad_k = None
    k = max(k_start, 1e-3)
    for _ in range(max_doublings + 1):
        m, b = probe(k)
        probes += 1
        if b:
            bad_k = k
            break
        good_k, good_m = k, m
        k *= 2
    if bad_k is not None and good_m is not None:
        lo, hi = good_k, bad_k
        for _ in range(bisect_iters):
            mid = (lo + hi) / 2
            m, b = probe(mid)
            probes += 1
            if b:
                hi = mid
            else:
                lo, good_k, good_m = mid, mid, m
    if good_m is None:
        # Breached at k_start: the frontier is below the first probe.
        return {
            "k_at_slo": 0.0,
            "debates_per_s": 0.0,
            "breached": True,
            "probes": probes,
            "at_frontier": m,
        }
    return {
        "k_at_slo": round(good_k, 4),
        "debates_per_s": good_m["debates_per_s"],
        "breached": bad_k is not None,
        "probes": probes,
        "at_frontier": good_m,
    }


def frontier_sweep(
    reqs: list[ReplayRequest],
    arms: list[ServeKnobs],
    slo: SLOSpec,
    *,
    k_start: float = 1.0,
    max_doublings: int = 4,
    bisect_iters: int = 2,
    log=lambda m: None,
) -> dict:
    frontier = {}
    for knobs in arms:
        log(f"sweeping arm {knobs.name()}")
        frontier[knobs.name()] = sweep_arm(
            reqs,
            knobs,
            slo,
            k_start=k_start,
            max_doublings=max_doublings,
            bisect_iters=bisect_iters,
            log=log,
        )
    return frontier


def bench_payload(
    frontier: dict,
    slo: SLOSpec,
    trace_note: str,
    *,
    platform: str = "cpu",
    baseline_path: Path | None = None,
) -> dict:
    """BENCH_capacity.json shape (bench_trend ``_CAPACITY_REQUIRED``).
    Headline = the FIRST arm's frontier (the baseline configuration);
    ``vs_baseline`` compares it against the committed file so a >10%
    frontier drop trips bench_trend."""
    first = next(iter(frontier.values()))
    value = float(first["debates_per_s"])
    vs = None
    if baseline_path and baseline_path.is_file():
        try:
            prev = json.loads(baseline_path.read_text(encoding="utf-8"))
            prev_v = float(prev.get("value", 0.0))
            if prev_v > 0:
                vs = round(value / prev_v, 4)
        except (ValueError, OSError):
            vs = None
    return {
        "metric": "serve_capacity_frontier_debates_per_s",
        "value": value,
        "unit": "accepted mock debates/s at the SLO frontier "
        "(open-loop seeded replay, first knob arm)",
        "vs_baseline": vs,
        "platform": platform,
        "within_budget": vs is None or vs >= 0.9,
        "frontier": frontier,
        "slo": {
            "ttft_p95_s": slo.ttft_p95_s,
            "max_shed_fraction": slo.max_shed_fraction,
        },
        "trace": trace_note,
        "escape_hatch": "harness-only: the daemon and scheduler are "
        "unchanged; delete BENCH_capacity.json to drop the gate",
    }


# -- CLI -------------------------------------------------------------------


def _default_arms() -> list[ServeKnobs]:
    return [ServeKnobs(replicas=1), ServeKnobs(replicas=3)]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replay", help="flight-recorder JSONL to replay")
    ap.add_argument("--seed", type=int, default=0, help="synthetic seed")
    ap.add_argument(
        "--requests", type=int, default=64, help="synthetic trace size"
    )
    ap.add_argument(
        "--rate", type=float, help="single run at this rate multiplier"
    )
    ap.add_argument(
        "--sweep", action="store_true", help="frontier sweep (two arms)"
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tiny seeded sweep (the lint_all replay-smoke stage)",
    )
    ap.add_argument("--slo-ttft-p95", type=float, default=0.5)
    ap.add_argument("--slo-shed", type=float, default=0.02)
    ap.add_argument("--bench-out", help="write BENCH-style payload here")
    ap.add_argument(
        "--events-out", help="dump armed flight-recorder JSONL after a "
        "--rate run (a recording replayable via --replay)"
    )
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    def log(msg: str) -> None:
        print(f"load_replay: {msg}", file=sys.stderr, flush=True)

    if args.replay:
        reqs, report = read_recording(args.replay)
        trace_note = (
            f"recorded {args.replay} ({report['requests']} requests, "
            f"{report['skipped']} skipped, torn_tail={report['torn_tail']})"
        )
        if not reqs:
            log(f"no replayable arrivals in {args.replay} ({report})")
            return 2
    else:
        n = 16 if args.smoke else args.requests
        reqs = synthesize(SynthSpec(seed=args.seed, requests=n))
        trace_note = f"synthetic seed={args.seed} requests={n}"
    log(f"trace: {trace_note}; tenant rates {tenant_rates(reqs)}")

    slo = SLOSpec(
        ttft_p95_s=args.slo_ttft_p95, max_shed_fraction=args.slo_shed
    )
    if args.rate is not None and not (args.sweep or args.smoke):
        res = replay_once(
            reqs, args.rate, events_out=args.events_out
        )
        breaches = slo_breaches(res.metrics, slo)
        payload = {**res.metrics, "slo_breaches": breaches}
        print(json.dumps(payload, indent=None if args.json else 2))
        return 0

    doublings, iters = (2, 1) if args.smoke else (4, 2)
    frontier = frontier_sweep(
        reqs,
        _default_arms(),
        slo,
        max_doublings=doublings,
        bisect_iters=iters,
        log=log,
    )
    payload = bench_payload(
        frontier,
        slo,
        trace_note,
        # The smoke's 16-request trace is not comparable to the
        # committed 64-request pin — its payload is schema-validated
        # only (vs_baseline null), never trend-compared.
        baseline_path=None if args.smoke else REPO / "BENCH_capacity.json",
    )
    out = json.dumps(payload, indent=2, sort_keys=True)
    if args.bench_out:
        Path(args.bench_out).write_text(out + "\n", encoding="utf-8")
        log(f"wrote {args.bench_out}")
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
