"""Stdlib mutation-testing runner (mutmut is not installable here).

Parity with the reference's mutation-hardened test practice
(reference skills/adversarial-spec/scripts/mutmut_config.py:4-119 and the
mutants documented in scripts/tests/test_models.py:88-95): generate small
semantic mutants of the pure-Python debate modules, run each module's test
file against every mutant, and report the kill score. A surviving mutant
is a behavior the tests do not pin.

Skip rules mirror mutmut_config.py: no mutants in prompt text, model-shape
tables, tests, or logging/help-string lines — the score measures *logic*.

Usage:
    python tools/mutation_run.py                 # default target set
    python tools/mutation_run.py --jobs 4 --out mutation_report.json
    python tools/mutation_run.py --only parsing  # one module
    python tools/mutation_run.py --show-survivors mutation_report.json

Mutation operators (one mutant per site):
    comparison flips    ==/!=, </<=, >/>=, in/not in, is/is not
    boolean operators   and/or swap, `not X` -> `X`
    arithmetic          +/-, * -> +
    constants           True/False flip, int n -> n+1, non-docstring
                        non-empty str s -> s + "XX"
    returns             `return expr` -> `return None`

Each worker process owns a disposable copy of the repo (package + tests),
mutates the target file there, and runs pytest on the mapped test file.
Exit code: 0 when the kill rate meets --fail-under (default 0 = report
only), 2 on baseline failure.
"""

from __future__ import annotations

import argparse
import ast
import copy
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from concurrent.futures import ProcessPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# module path (repo-relative) -> test files that must kill its mutants
DEFAULT_TARGETS: dict[str, list[str]] = {
    "adversarial_spec_tpu/debate/parsing.py": ["tests/test_parsing.py"],
    "adversarial_spec_tpu/debate/usage.py": ["tests/test_usage.py"],
    "adversarial_spec_tpu/debate/session.py": [
        "tests/test_session.py",
        "tests/test_durability.py",
    ],
    "adversarial_spec_tpu/debate/journal.py": ["tests/test_durability.py"],
    "adversarial_spec_tpu/debate/profiles.py": ["tests/test_profiles.py"],
    "adversarial_spec_tpu/debate/core.py": ["tests/test_engine_mock.py"],
    "adversarial_spec_tpu/debate/telegram.py": ["tests/test_telegram.py"],
    "adversarial_spec_tpu/debate/types.py": [
        "tests/test_engine_mock.py",
        "tests/test_parsing.py",
    ],
    "adversarial_spec_tpu/cli.py": ["tests/test_cli.py"],
    "adversarial_spec_tpu/utils/tracing.py": ["tests/test_tracing.py"],
}

# Lines containing these markers are not mutated. Imported from
# mutmut_config.py (single source of truth — the two lists previously had
# to be updated in lockstep by hand, ADVICE r5); loaded by file path so
# `python tools/mutation_run.py` works without the repo root on sys.path.
def _load_skip_markers() -> tuple[str, ...]:
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mutmut_config", REPO / "mutmut_config.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module._SKIP_LINE_MARKERS


SKIP_LINE_MARKERS = _load_skip_markers()

_CMP_SWAP = {
    ast.Eq: ast.NotEq,
    ast.NotEq: ast.Eq,
    ast.Lt: ast.LtE,
    ast.LtE: ast.Lt,
    ast.Gt: ast.GtE,
    ast.GtE: ast.Gt,
    ast.In: ast.NotIn,
    ast.NotIn: ast.In,
    ast.Is: ast.IsNot,
    ast.IsNot: ast.Is,
}
_BIN_SWAP = {ast.Add: ast.Sub, ast.Sub: ast.Add, ast.Mult: ast.Add}


_LOG_CALL_NAMES = {"print", "_err"}


def _log_call_lines(tree: ast.AST) -> set[int]:
    """Every line spanned by a print()/_err() call: the line-marker skip
    misses multi-line logging calls, so mark their whole span (logging
    text is excluded from mutation by design — mutmut_config.py)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in _LOG_CALL_NAMES
        ):
            out.update(range(node.lineno, (node.end_lineno or node.lineno) + 1))
    return out


def _annotation_positions(tree: ast.AST) -> set[tuple[int, int]]:
    """(lineno, col) of constants inside annotations — runtime-inert under
    ``from __future__ import annotations`` (every module here), so mutating
    them can only produce equivalent mutants."""
    out: set[tuple[int, int]] = set()

    def mark(sub: ast.AST | None) -> None:
        if sub is None:
            return
        for n in ast.walk(sub):
            if isinstance(n, ast.Constant):
                out.add((n.lineno, n.col_offset))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mark(node.returns)
            a = node.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                mark(p.annotation)
            if a.vararg:
                mark(a.vararg.annotation)
            if a.kwarg:
                mark(a.kwarg.annotation)
        elif isinstance(node, ast.AnnAssign):
            mark(node.annotation)
    return out


def _docstring_positions(tree: ast.AST) -> set[int]:
    """Line numbers of docstring constants (never mutated)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(body[0].value.lineno)
    return out


class _SiteCollector(ast.NodeVisitor):
    """Enumerate mutation sites; each site is (kind, lineno, detail)."""

    def __init__(
        self,
        skip_lines: set[int],
        doc_lines: set[int],
        ann_pos: set[tuple[int, int]] = frozenset(),
    ):
        self.sites: list[tuple[str, int, str]] = []
        self.skip_lines = skip_lines
        self.doc_lines = doc_lines
        self.ann_pos = ann_pos

    def _ok(self, node: ast.AST) -> bool:
        return getattr(node, "lineno", 0) not in self.skip_lines

    def visit_Compare(self, node: ast.Compare) -> None:
        if self._ok(node):
            for i, op in enumerate(node.ops):
                if type(op) in _CMP_SWAP:
                    self.sites.append(
                        ("cmp", node.lineno, f"{type(op).__name__}@{i}")
                    )
        self.generic_visit(node)

    def visit_BoolOp(self, node: ast.BoolOp) -> None:
        if self._ok(node):
            self.sites.append(("bool", node.lineno, type(node.op).__name__))
        self.generic_visit(node)

    def visit_UnaryOp(self, node: ast.UnaryOp) -> None:
        if self._ok(node) and isinstance(node.op, ast.Not):
            self.sites.append(("not", node.lineno, "Not"))
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if self._ok(node) and type(node.op) in _BIN_SWAP:
            self.sites.append(("bin", node.lineno, type(node.op).__name__))
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if (
            self._ok(node)
            and node.lineno not in self.doc_lines
            and (node.lineno, node.col_offset) not in self.ann_pos
        ):
            if node.value is True or node.value is False:
                self.sites.append(("const-bool", node.lineno, str(node.value)))
            elif isinstance(node.value, int) and not isinstance(
                node.value, bool
            ):
                self.sites.append(("const-int", node.lineno, str(node.value)))
            elif isinstance(node.value, str) and node.value:
                self.sites.append(
                    ("const-str", node.lineno, node.value[:20])
                )
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if (
            self._ok(node)
            and node.value is not None
            and not (
                isinstance(node.value, ast.Constant)
                and node.value.value is None
            )
        ):
            self.sites.append(("return", node.lineno, "None"))
        self.generic_visit(node)


class _Mutator(ast.NodeTransformer):
    """Apply exactly the site with index ``target`` (collector order)."""

    def __init__(
        self,
        target: int,
        skip_lines: set[int],
        doc_lines: set[int],
        ann_pos: set[tuple[int, int]] = frozenset(),
    ):
        self.target = target
        self.counter = -1
        self.applied: str | None = None
        self.skip_lines = skip_lines
        self.doc_lines = doc_lines
        self.ann_pos = ann_pos

    def _hit(self) -> bool:
        self.counter += 1
        return self.counter == self.target

    def _ok(self, node: ast.AST) -> bool:
        return getattr(node, "lineno", 0) not in self.skip_lines

    def visit_Compare(self, node: ast.Compare) -> ast.AST:
        if self._ok(node):
            for i, op in enumerate(node.ops):
                if type(op) in _CMP_SWAP:
                    if self._hit():
                        node = copy.deepcopy(node)
                        node.ops[i] = _CMP_SWAP[type(op)]()
                        self.applied = (
                            f"L{node.lineno}: {type(op).__name__} -> "
                            f"{type(node.ops[i]).__name__}"
                        )
                        return self.generic_visit(node)
        return self.generic_visit(node)

    def visit_BoolOp(self, node: ast.BoolOp) -> ast.AST:
        if self._ok(node) and self._hit():
            new_op = ast.Or() if isinstance(node.op, ast.And) else ast.And()
            self.applied = (
                f"L{node.lineno}: {type(node.op).__name__} -> "
                f"{type(new_op).__name__}"
            )
            node.op = new_op
        return self.generic_visit(node)

    def visit_UnaryOp(self, node: ast.UnaryOp) -> ast.AST:
        if self._ok(node) and isinstance(node.op, ast.Not):
            if self._hit():
                self.applied = f"L{node.lineno}: drop `not`"
                return self.generic_visit(node.operand)
        return self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> ast.AST:
        if self._ok(node) and type(node.op) in _BIN_SWAP:
            if self._hit():
                new_op = _BIN_SWAP[type(node.op)]()
                self.applied = (
                    f"L{node.lineno}: {type(node.op).__name__} -> "
                    f"{type(new_op).__name__}"
                )
                node.op = new_op
        return self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> ast.AST:
        if (
            self._ok(node)
            and node.lineno not in self.doc_lines
            and (node.lineno, node.col_offset) not in self.ann_pos
        ):
            if node.value is True or node.value is False:
                if self._hit():
                    self.applied = f"L{node.lineno}: {node.value} flipped"
                    return ast.copy_location(
                        ast.Constant(value=not node.value), node
                    )
            elif isinstance(node.value, int) and not isinstance(
                node.value, bool
            ):
                if self._hit():
                    self.applied = (
                        f"L{node.lineno}: {node.value} -> {node.value + 1}"
                    )
                    return ast.copy_location(
                        ast.Constant(value=node.value + 1), node
                    )
            elif isinstance(node.value, str) and node.value:
                if self._hit():
                    self.applied = f"L{node.lineno}: str + 'XX'"
                    return ast.copy_location(
                        ast.Constant(value=node.value + "XX"), node
                    )
        return node

    def visit_Return(self, node: ast.Return) -> ast.AST:
        if (
            self._ok(node)
            and node.value is not None
            and not (
                isinstance(node.value, ast.Constant)
                and node.value.value is None
            )
        ):
            if self._hit():
                self.applied = f"L{node.lineno}: return -> return None"
                return ast.copy_location(
                    ast.Return(value=None), node
                )
        return self.generic_visit(node)


def _main_guard_lines(tree: ast.AST) -> set[int]:
    """Lines of ``if __name__ == "__main__":`` blocks — module-entry glue
    (the entrypoints are pinned by suite-level subprocess tests, which
    are skipped during sweeps for speed — see ADVSPEC_MUTATION)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.If)
            and isinstance(node.test, ast.Compare)
            and isinstance(node.test.left, ast.Name)
            and node.test.left.id == "__name__"
        ):
            out.update(range(node.lineno, (node.end_lineno or node.lineno) + 1))
    return out


def _skip_lines(src: str, tree: ast.AST) -> set[int]:
    return (
        {
            i + 1
            for i, line in enumerate(src.splitlines())
            if any(m in line for m in SKIP_LINE_MARKERS)
        }
        | _log_call_lines(tree)
        | _main_guard_lines(tree)
    )


def enumerate_mutants(src: str) -> list[tuple[str, int, str]]:
    tree = ast.parse(src)
    collector = _SiteCollector(
        _skip_lines(src, tree),
        _docstring_positions(tree),
        _annotation_positions(tree),
    )
    collector.visit(tree)
    return collector.sites


def make_mutant(src: str, index: int) -> tuple[str, str]:
    """Return (mutated_source, description) for site ``index``."""
    tree = ast.parse(src)
    m = _Mutator(
        index,
        _skip_lines(src, tree),
        _docstring_positions(tree),
        _annotation_positions(tree),
    )
    new_tree = ast.fix_missing_locations(m.visit(tree))
    if m.applied is None:
        raise IndexError(f"no mutation site {index}")
    return ast.unparse(new_tree), m.applied


# ----------------------------------------------------------------- runner

_WORKER_TREE: Path | None = None


def _worker_tree() -> Path:
    """Per-process disposable repo copy (package + tests + conftest)."""
    global _WORKER_TREE
    if _WORKER_TREE is None:
        import atexit

        root = Path(tempfile.mkdtemp(prefix=f"mut-{os.getpid()}-"))
        atexit.register(shutil.rmtree, root, ignore_errors=True)
        for rel in ("adversarial_spec_tpu", "tests"):
            shutil.copytree(
                REPO / rel,
                root / rel,
                ignore=shutil.ignore_patterns("__pycache__"),
            )
        (root / "pyproject.toml").write_text(
            "[tool.pytest.ini_options]\n", encoding="utf-8"
        )
        _WORKER_TREE = root
    return _WORKER_TREE


def _run_pytest(tree: Path, test_files: list[str], timeout: float) -> str:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tree)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # Subprocess-spawning entrypoint tests skip under this flag: a fresh
    # interpreter boot per mutant would dominate sweep wall-clock.
    env["ADVSPEC_MUTATION"] = "1"
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "pytest",
                "-x",
                "-q",
                "--no-header",
                "-p",
                "no:cacheprovider",
                *test_files,
            ],
            cwd=tree,
            env=env,
            capture_output=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return "timeout"
    return "passed" if proc.returncode == 0 else "failed"


def _eval_mutant(job: tuple) -> dict:
    module_rel, index, test_files, timeout = job
    tree = _worker_tree()
    target = tree / module_rel
    original = (REPO / module_rel).read_text(encoding="utf-8")
    mutated, desc = make_mutant(original, index)
    target.write_text(mutated, encoding="utf-8")
    try:
        t0 = time.monotonic()
        status = _run_pytest(tree, test_files, timeout)
        return {
            "module": module_rel,
            "index": index,
            "mutation": desc,
            # tests failed on the mutant => the mutant was KILLED
            "status": {
                "failed": "killed",
                "timeout": "timeout-killed",
                "passed": "survived",
            }[status],
            "seconds": round(time.monotonic() - t0, 2),
        }
    finally:
        target.write_text(original, encoding="utf-8")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=4)
    ap.add_argument("--only", help="substring filter on module path")
    ap.add_argument("--max-mutants", type=int, default=0)
    ap.add_argument("--out", default="mutation_report.json")
    ap.add_argument(
        "--fail-under",
        type=float,
        default=0.0,
        help="minimum kill rate in percent (0 = report only)",
    )
    ap.add_argument(
        "--show-survivors",
        metavar="REPORT",
        help="print survivors from an existing report and exit",
    )
    args = ap.parse_args(argv)

    if args.show_survivors:
        report = json.loads(Path(args.show_survivors).read_text())
        for r in report["results"]:
            if r["status"] == "survived":
                print(f"{r['module']} #{r['index']:<4} {r['mutation']}")
        return 0

    targets = {
        m: t
        for m, t in DEFAULT_TARGETS.items()
        if not args.only or args.only in m
    }
    if not targets:
        print(f"no targets match --only {args.only!r}", file=sys.stderr)
        return 2

    # Baseline: unmutated tests must be green, and the runtime sets the
    # per-mutant timeout (generous 5x + 30 s: a hung mutant counts killed).
    timeouts: dict[str, float] = {}
    for module_rel, test_files in targets.items():
        t0 = time.monotonic()
        status = _run_pytest(REPO, test_files, timeout=600)
        base = time.monotonic() - t0
        if status != "passed":
            print(
                f"baseline {status} for {test_files} — fix tests first",
                file=sys.stderr,
            )
            return 2
        timeouts[module_rel] = base * 5 + 30

    jobs = []
    for module_rel, test_files in targets.items():
        src = (REPO / module_rel).read_text(encoding="utf-8")
        sites = enumerate_mutants(src)
        if args.max_mutants:
            sites = sites[: args.max_mutants]
        jobs += [
            (module_rel, i, test_files, timeouts[module_rel])
            for i in range(len(sites))
        ]
    print(f"{len(jobs)} mutants over {len(targets)} modules")

    results = []
    t0 = time.monotonic()
    with ProcessPoolExecutor(max_workers=args.jobs) as pool:
        for n, res in enumerate(pool.map(_eval_mutant, jobs), 1):
            results.append(res)
            if n % 25 == 0 or n == len(jobs):
                killed = sum(
                    r["status"] != "survived" for r in results
                )
                print(
                    f"  {n}/{len(jobs)} evaluated, "
                    f"{killed} killed, {n - killed} survived "
                    f"({time.monotonic() - t0:.0f}s)"
                )

    by_module: dict[str, dict[str, int]] = {}
    for r in results:
        d = by_module.setdefault(
            r["module"], {"killed": 0, "survived": 0}
        )
        d["killed" if r["status"] != "survived" else "survived"] += 1
    total = len(results)
    killed = sum(r["status"] != "survived" for r in results)
    score = 100.0 * killed / total if total else 0.0

    report = {
        "score_percent": round(score, 1),
        "killed": killed,
        "survived": total - killed,
        "total": total,
        "by_module": by_module,
        "results": results,
    }
    Path(args.out).write_text(json.dumps(report, indent=1), encoding="utf-8")
    print(f"\nmutation score: {score:.1f}% ({killed}/{total} killed)")
    for mod, d in sorted(by_module.items()):
        sub = d["killed"] + d["survived"]
        print(
            f"  {mod}: {100.0 * d['killed'] / sub:.1f}% "
            f"({d['killed']}/{sub})"
        )
    print(f"report: {args.out}")
    if args.fail_under and score < args.fail_under:
        print(
            f"FAIL: score {score:.1f}% < --fail-under {args.fail_under}%",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
