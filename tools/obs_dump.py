"""Offline pretty-printer / validator for flight-recorder event JSONL.

The serving path dumps events (``--events-out``, fault/timeout
auto-dumps); this tool is the triage half: it schema-checks EVERY line
against the event vocabulary (adversarial_spec_tpu/obs/events.py — the
schemas are derived from the dataclasses, so they cannot drift from the
emitters) and renders a per-step occupancy timeline as text, the
"what was the batcher doing" view docs/observability.md walks through.

Usage:
    python tools/obs_dump.py events.jsonl              # validate + summary
    python tools/obs_dump.py events.jsonl --timeline   # + occupancy bars
    python tools/obs_dump.py events.jsonl --requests   # + per-request log
    python tools/obs_dump.py events.jsonl --trace ID   # one round only

``--trace`` scopes every view to one causal trace (one debate round;
obs/trace.py id model) — validation still covers EVERY line, so a
scoped view can't hide a schema violation elsewhere in the dump. The
per-request waterfall/critical-path view lives in tools/trace_view.py.

Exit codes: 0 = every line valid; 1 = schema violations (listed on
stderr); 2 = unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from adversarial_spec_tpu.obs.events import validate_event  # noqa: E402

_STEP_GLYPH = {"fused": "#", "decode": "=", "prefill": "."}


def load_events(path: str) -> tuple[list[dict], list[str]]:
    """Parse + schema-check a JSONL dump. Returns (valid events,
    per-line error strings)."""
    events: list[dict] = []
    errors: list[str] = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: not JSON ({e})")
                continue
            problems = validate_event(obj)
            if problems:
                errors.extend(f"line {lineno}: {p}" for p in problems)
            else:
                events.append(obj)
    return events, errors


def summarize(events: list[dict]) -> str:
    by_type: dict[str, int] = {}
    for e in events:
        by_type[e["type"]] = by_type.get(e["type"], 0) + 1
    parts = [f"{n} {t}" for t, n in sorted(by_type.items())]
    lines = [f"{len(events)} event(s): " + (", ".join(parts) or "none")]
    faults = [e for e in events if e["type"] == "fault"]
    for f in faults:
        lines.append(
            f"  fault: {f['kind']} at {f['seam']} "
            f"(req {f['req_id']}, slot {f['slot']}, "
            f"{f['pages_freed']} page(s) freed, "
            f"{'requeued' if f['requeued'] else 'evicted'})"
        )
    cancels = [e for e in events if e["type"] == "cancel"]
    if cancels:
        saved = sum(c["tokens_saved"] for c in cancels)
        lines.append(
            f"  {len(cancels)} early cancellation(s): {saved} decode "
            "token(s) saved"
        )
    quarantined = [
        e
        for e in events
        if e["type"] == "swap" and e["op"] == "quarantine"
    ]
    if quarantined:
        lines.append(
            f"  WARNING: {len(quarantined)} corrupt KV store entr"
            f"{'y' if len(quarantined) == 1 else 'ies'} quarantined"
        )
    ships = [
        e for e in events if e["type"] == "swap" and e["op"] == "ship"
    ]
    prefetches = [
        e for e in events if e["type"] == "swap" and e["op"] == "prefetch"
    ]
    handoff_routes = [
        e for e in events if e["type"] == "route" and e["reason"] == "prefill"
    ]
    if ships or prefetches or handoff_routes:
        lines.append(
            "  kv handoff: "
            f"{len(handoff_routes)} prefill-routed request(s), "
            f"{sum(s['blocks'] for s in ships)} block(s) shipped "
            f"({len(ships)} publication(s)), "
            f"{sum(p['blocks'] for p in prefetches)} block(s) found at "
            f"prefetch"
        )
    weights = [e for e in events if e["type"] == "weight"]
    if weights:
        ops: dict[str, int] = {}
        for w in weights:
            ops[w["op"]] = ops.get(w["op"], 0) + 1
        lines.append(
            "  weight residency: "
            + ", ".join(f"{op}={n}" for op, n in sorted(ops.items()))
        )
        if ops.get("swap_fault"):
            lines.append(
                f"  WARNING: {ops['swap_fault']} weight swap(s) aborted "
                "mid-promotion (host entries intact; admission retried)"
            )
    compiles = [e for e in events if e["type"] == "compile"]
    unexpected = [c for c in compiles if c["unexpected"]]
    if unexpected:
        lines.append(
            f"  WARNING: {len(unexpected)} unexpected jit recompile(s): "
            + ", ".join(sorted({c["program"] for c in unexpected}))
        )
    hops = [
        e for e in events if e["type"] == "route" and e["hop"] > 0
    ]
    if hops:
        lines.append(
            f"  {len(hops)} failover hop(s): "
            + ", ".join(
                f"req {h['req_id']}->{h['replica']} ({h['reason']})"
                for h in hops
            )
        )
    retired = [
        e
        for e in events
        if e["type"] == "replica" and e["op"] in ("retire", "heartbeat_miss")
    ]
    for r in retired:
        lines.append(
            f"  WARNING: replica {r['replica']} {r['op']}"
            + (f" ({r['reason']})" if r["reason"] else "")
            + f", {r['alive']} left"
        )
    scales = [e for e in events if e["type"] == "scale"]
    if scales:
        ops: dict[str, int] = {}
        for s in scales:
            ops[s["op"]] = ops.get(s["op"], 0) + 1
        lines.append(
            "  autoscaler: "
            + ", ".join(f"{op}={n}" for op, n in sorted(ops.items()))
        )
        if ops.get("spawn_failed"):
            lines.append(
                f"  WARNING: {ops['spawn_failed']} scale-out(s) aborted "
                "before ring admission (spawn/warm failure; no request "
                "ever routed there)"
            )
    serve = [e for e in events if e["type"] == "serve"]
    if serve:
        sheds: dict[str, int] = {}
        preempted = drained = brownouts = 0
        for s in serve:
            if s["op"] == "shed":
                sheds[s["reason"]] = sheds.get(s["reason"], 0) + 1
            elif s["op"] == "preempted":
                preempted += 1
            elif s["op"] == "drained":
                drained += 1
            elif s["op"] == "brownout_enter":
                brownouts += 1
        if sheds:
            lines.append(
                f"  {sum(sheds.values())} typed load-shed refusal(s): "
                + ", ".join(f"{r}={n}" for r, n in sorted(sheds.items()))
            )
        if preempted:
            lines.append(
                f"  {preempted} batch unit(s) preempted for tier pressure"
            )
        if drained:
            lines.append(
                f"  {drained} unit(s) drained at shutdown "
                "(journal-resumable)"
            )
        if brownouts:
            lines.append(f"  WARNING: brownout entered {brownouts} time(s)")
        arrivals = [
            s
            for s in serve
            if s["op"] in ("accepted", "shed")
            and s.get("arrival_s", 0) > 0
        ]
        if arrivals:
            # Armed recording: per-tenant mean arrival rate over the
            # recorded span — the at-a-glance shape of the trace
            # load_replay would reconstruct from this dump.
            span = max(s["arrival_s"] for s in arrivals) - min(
                s["arrival_s"] for s in arrivals
            )
            counts: dict[str, int] = {}
            for s in arrivals:
                counts[s["tenant"]] = counts.get(s["tenant"], 0) + 1
            # A degenerate window (one arrival, zero span) has no
            # meaningful rate — show counts instead of a silly number.
            lines.append(
                f"  arrivals: {len(arrivals)} over {span:.3f}s — "
                + ", ".join(
                    f"{t}={n / span:.1f}/s" if span > 1e-6 else f"{t}={n}"
                    for t, n in sorted(counts.items())
                )
            )
    return "\n".join(lines)


def occupancy_timeline(events: list[dict], width: int = 16) -> str:
    """Per-step occupancy bars: one row per StepEvent, slot occupancy as
    a bar, the step kind as the glyph, annotations for the riding
    admission / sync reason — the step-by-step 'what was the batcher
    doing' view. When the dump carries SwapEvents (tiered KV,
    engine/kvtier.py) each step row is additionally annotated with the
    per-tier residency as of that step (host/disk block counts trail
    the most recent swap), and the swaps themselves print inline. A
    fleet dump (Route/ReplicaEvents, fleet/router.py) adds a replica
    column: each step row carries the replica most recently routed to
    (``rep=``), and the routing decisions / replica lifecycle
    transitions print inline where they happened. A serve-daemon dump
    (ServeEvents, adversarial_spec_tpu/serve) adds a TENANT column:
    each step row carries the tenant whose unit most recently started
    running (``ten=``) so interleaved concurrent debates read apart,
    and the admission/shed/preempt/brownout transitions print inline
    with their typed reasons and post-op backlog."""
    steps = [
        e
        for e in events
        if e["type"]
        in (
            "step",
            "swap",
            "weight",
            "span",
            "cancel",
            "route",
            "replica",
            "scale",
            "serve",
        )
    ]
    if not any(e["type"] == "step" for e in steps):
        return "(no step events)"
    max_live = max(
        max(s["n_live"] for s in steps if s["type"] == "step"), 1
    )
    scale = max(max_live, 1)
    tiered = any(e["type"] == "swap" for e in steps)
    fleet = any(
        e["type"] in ("route", "replica", "scale") for e in steps
    )
    serving = any(e["type"] == "serve" for e in steps)
    rows = []
    host_res = disk_res = 0
    cur_replica = ""
    cur_tenant = ""
    for s in steps:
        if s["type"] == "serve":
            # Daemon lifecycle/pressure transitions inline: WHO was
            # admitted/shed/preempted, under WHAT backlog. The running
            # op also drives the step rows' tenant column.
            glyph = {
                "shed": "x",
                "preempted": "x",
                "drained": "x",
                "brownout_enter": "!",
                "brownout_exit": "!",
            }.get(s["op"], "+")
            if s["op"] == "running":
                cur_tenant = s["tenant"]
            notes = []
            if s["tenant"]:
                notes.append(f"{s['tenant']}/{s['tier']}")
            if s["debate"]:
                notes.append(
                    s["debate"]
                    + (f"#{s['index']}" if s["index"] >= 0 else "")
                )
            if s["reason"]:
                notes.append(f"({s['reason']})")
            notes.append(f"backlog={s['backlog_tokens']}")
            if s.get("arrival_s", 0) > 0:
                # Armed recording (ADVSPEC_OBS_ARRIVALS): lead with the
                # arrival offset so the admission edges read as a
                # schedule — the column load_replay reconstructs from.
                notes.insert(0, f"@{s['arrival_s']:.3f}s")
            rows.append(
                f"seq {s['seq']:>6} [{glyph * width}] "
                f"{'serve:' + s['op']:<13} " + " ".join(notes)
            )
            continue
        if s["type"] == "span":
            # Trace-span boundaries print inline so the timeline shows
            # WHERE in the step stream each request's stages opened and
            # closed (wall on the end rows; trace_view.py renders the
            # per-request waterfall proper).
            notes = []
            if s["req_id"] >= 0:
                notes.append(f"req={s['req_id']}")
            if s["phase"] == "end" and s["wall_s"]:
                notes.append(f"{s['wall_s']:.4f}s")
            if s["span_id"]:
                notes.append(s["span_id"])
            elif s["trace_id"]:
                notes.append(s["trace_id"])
            glyph = (
                ">"
                if s["phase"] == "begin"
                else "x" if s["phase"] == "cancelled" else "<"
            )
            rows.append(
                f"seq {s['seq']:>6} [{glyph * width}] "
                f"{s['name'] + ':' + s['phase']:<13} " + " ".join(notes)
            )
            continue
        if s["type"] == "cancel":
            # A truncated request: the cancel row shows what was
            # emitted and what the cancellation saved, inline where it
            # happened in the step stream.
            rows.append(
                f"seq {s['seq']:>6} [{'x' * width}] "
                f"{'cancel':<8} req={s['req_id']} slot={s['slot']} "
                f"emitted={s['tokens_emitted']}tok "
                f"saved={s['tokens_saved']}tok ({s['reason']})"
            )
            continue
        if s["type"] == "route":
            cur_replica = s["replica"]
            notes = [f"req={s['req_id']}"]
            if s["hop"]:
                notes.append(f"hop={s['hop']}")
            notes.append(f"key={s['key'][:12]}")
            rows.append(
                f"seq {s['seq']:>6} [{'>' * width}] "
                f"{'route>' + s['replica']:<13} "
                f"{s['reason']} " + " ".join(notes)
            )
            continue
        if s["type"] == "scale":
            # Autoscaler lifecycle transitions inline: which replica
            # moved through which elasticity state, under what backlog,
            # and the desired-vs-alive membership it left behind.
            rows.append(
                f"seq {s['seq']:>6} [{'~' * width}] "
                f"{'scale:' + s['op']:<13} "
                + " ".join(
                    n
                    for n in (
                        s["replica"],
                        s["direction"] and f"dir={s['direction']}",
                        s["reason"],
                        f"desired={s['desired']}",
                        f"alive={s['alive']}",
                        f"backlog={s['backlog_tokens']}",
                    )
                    if n
                )
            )
            continue
        if s["type"] == "replica":
            rows.append(
                f"seq {s['seq']:>6} [{'!' * width}] "
                f"{'replica:' + s['op']:<13} "
                + " ".join(
                    n
                    for n in (
                        s["replica"],
                        s["reason"],
                        f"alive={s['alive']}",
                    )
                    if n
                )
            )
            continue
        if s["type"] == "weight":
            # Weight-residency transitions inline: WHICH model swapped,
            # what it cost, and the post-op resident/host pool split —
            # residency thrash reads as a run of w:load rows that
            # should have been w:promote.
            notes = [s["alias"] or "?"]
            if s["nbytes"]:
                notes.append(f"{s['nbytes'] >> 20}MiB")
            if s["wall_s"]:
                notes.append(f"{s['wall_s']:.4f}s")
            notes.append(f"res={s['resident']}")
            notes.append(f"host={s['host']}")
            glyph = "!" if s["op"] == "swap_fault" else "w"
            rows.append(
                f"seq {s['seq']:>6} [{glyph * width}] "
                f"{'w:' + s['op']:<13} " + " ".join(notes)
            )
            continue
        if s["type"] == "swap":
            host_res, disk_res = s["host_resident"], s["disk_resident"]
            notes = [f"{s['blocks']} block(s)", f"{s['tokens']}tok"]
            if s["slot"] >= 0:
                notes.append(f"slot={s['slot']}")
            notes.append(f"host={host_res}")
            notes.append(f"disk={disk_res}")
            rows.append(
                f"seq {s['seq']:>6} [{'~' * width}] "
                f"{s['op'] + '>' + s['tier']:<8} " + " ".join(notes)
            )
            continue
        glyph = _STEP_GLYPH.get(s["kind"], "?")
        filled = round(s["n_live"] / scale * width)
        bar = glyph * filled + "-" * (width - filled)
        notes = [f"live={s['n_live']}"]
        if s["admission_slot"] >= 0:
            notes.append(
                f"adm@{s['admission_slot']}+{s['prefill_tokens']}tok"
            )
        if s["pipeline_depth"]:
            notes.append(f"depth={s['pipeline_depth']}")
        if s["sync_reason"]:
            notes.append(f"sync={s['sync_reason']}")
        if tiered:
            notes.append(f"host={host_res}")
            notes.append(f"disk={disk_res}")
        if fleet:
            notes.append(f"rep={cur_replica or '?'}")
        if serving:
            notes.append(f"ten={cur_tenant or '?'}")
        rows.append(
            f"seq {s['seq']:>6} [{bar}] {s['kind']:<8} " + " ".join(notes)
        )
    n_steps = sum(1 for s in steps if s["type"] == "step")
    spanned = any(e["type"] == "span" for e in steps)
    cancelled = any(e["type"] == "cancel" for e in steps)
    legend = (
        f"occupancy timeline ({n_steps} step(s), max live {max_live}; "
        "#=fused ==decode .=prefill"
        + ("; ~=tier swap, host/disk=resident blocks" if tiered else "")
        + (
            "; w=weight swap, res/host=resident models"
            if any(e["type"] == "weight" for e in steps)
            else ""
        )
        + ("; >=span begin <=span end" if spanned else "")
        + ("; x=early cancel" if cancelled else "")
        + ("; rep=last routed replica, !=replica lifecycle" if fleet else "")
        + (
            "; ~=autoscaler transition (desired vs alive)"
            if any(e["type"] == "scale" for e in steps)
            else ""
        )
        + (
            "; ten=last running tenant, +=serve admit/finish, "
            "x=shed/preempt/drain, !=brownout"
            if serving
            else ""
        )
        + ")"
    )
    return "\n".join([legend] + rows)


def request_log(events: list[dict]) -> str:
    """Per-request lifecycle, in event order."""
    reqs = [e for e in events if e["type"] == "request"]
    if not reqs:
        return "(no request events)"
    # Armed recordings lead with the arrival offset (@t) so the log
    # reads as a schedule; unarmed dumps keep the old column set.
    timed = any(r.get("arrival_s", 0) > 0 for r in reqs)
    rows = []
    for r in reqs:
        extra = (
            f" cached={r['cached_tokens']}" if r["cached_tokens"] else ""
        )
        if r.get("span_id"):
            extra += f" span={r['span_id']}"
        at = ""
        if timed:
            a = r.get("arrival_s", 0)
            at = f"@{a:8.3f}s " if a > 0 else " " * 11
        rows.append(
            f"{at}seq {r['seq']:>6} req {r['req_id']:>3} "
            f"{r['state']:<9} slot={r['slot']} tokens={r['tokens']}{extra}"
        )
    return "\n".join(rows)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="events JSONL file to validate/render")
    ap.add_argument(
        "--timeline",
        action="store_true",
        help="render the per-step occupancy timeline",
    )
    ap.add_argument(
        "--requests",
        action="store_true",
        help="render the per-request lifecycle log",
    )
    ap.add_argument(
        "--trace",
        help="scope the rendered views to one trace id (one debate "
        "round); validation still covers every line",
    )
    args = ap.parse_args(argv)
    try:
        events, errors = load_events(args.path)
    except OSError as e:
        print(f"obs_dump: {e}", file=sys.stderr)
        return 2
    if args.trace:
        events = [e for e in events if e.get("trace_id") == args.trace]
    print(summarize(events))
    if args.timeline:
        print()
        print(occupancy_timeline(events))
    if args.requests:
        print()
        print(request_log(events))
    for err in errors:
        print(f"obs_dump: {err}", file=sys.stderr)
    if errors:
        print(
            f"obs_dump: {len(errors)} schema violation(s)", file=sys.stderr
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
