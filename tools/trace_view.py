"""Per-request latency waterfalls from flight-recorder event JSONL.

The serving path stamps every event with causal trace/span ids
(obs/trace.py) and emits per-request stage spans (queued → prefill →
decode under a ``request`` envelope). This tool is the triage half: it
reconstructs each request's waterfall, prints the critical path per
round, and — the load-bearing part — **checks** the decomposition: a
request's stage walls (prefill + decode) must sum to its reported
service wall within tolerance. A waterfall that doesn't add up is a
telemetry bug, and this tool treats it as one (exit 1), so the
decomposition stays checked, not decorative.

A disaggregated fleet dump (reason="prefill" RouteEvents + "ship"
SwapEvents, fleet/router.py + fleet/handoff.py) additionally annotates
each handed-off request's waterfall head with the handoff path —
``prefill@r0 -> decode@r2 (N blocks shipped)`` — so the cross-replica
KV handoff is readable straight off the view.

Usage:
    python tools/trace_view.py events.jsonl               # waterfalls + check
    python tools/trace_view.py events.jsonl --trace ID    # one round only
    python tools/trace_view.py events.jsonl --json        # machine-readable

Exit codes: 0 = every request's decomposition checks out; 1 = a sum
violation or schema error; 2 = unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.obs_dump import load_events  # noqa: E402

# |request_wall - (prefill + decode)| must stay within
# max(ABS_TOL, REL_TOL * request_wall). The scheduler computes the
# envelope as exactly prefill + decode, and the mock's synthetic
# seconds are exact binary fractions — the tolerance only absorbs the
# dump-time 6-decimal rounding of each float.
ABS_TOL = 1e-5
REL_TOL = 0.01

# Stage render order in a waterfall row.
STAGES = ("queued", "prefill", "decode")


def collect_requests(events: list[dict]) -> dict[str, dict]:
    """Group span events by span_id into per-request records:
    ``{span_id: {trace_id, req_id, begin_seq, stages: {name: wall},
    ended, extra}}``. A re-emitted stage (a requeued request prefilling
    twice) keeps the LAST end wall — the one the request actually paid
    on its surviving attempt. Fleet RouteEvents stamped with the span
    collect under ``route`` (in seq order), so a failover hop — the
    request leaving a dead replica for a survivor — is visible right
    in the waterfall head instead of only in the raw dump."""
    out: dict[str, dict] = {}
    routes: dict[str, list[dict]] = {}
    ships: dict[str, int] = {}
    # Armed recordings (ADVSPEC_OBS_ARRIVALS) stamp the queue-edge
    # RequestEvent with the monotonic arrival offset; carry it onto the
    # span record by req_id so the waterfall head shows WHEN each
    # request entered, not just how long its stages took.
    arrivals: dict[int, float] = {}
    for e in events:
        if (
            e["type"] == "request"
            and e.get("state") == "queued"
            and e.get("arrival_s", 0) > 0
        ):
            arrivals[e["req_id"]] = e["arrival_s"]
        if (
            e["type"] == "swap"
            and e["op"] == "ship"
            and e.get("span_id")
        ):
            # Handoff publications stamped with the request's span: the
            # block count feeds the waterfall's handoff annotation.
            ships[e["span_id"]] = ships.get(e["span_id"], 0) + e["blocks"]
            continue
        if e["type"] == "route" and e.get("span_id"):
            routes.setdefault(e["span_id"], []).append(
                {
                    "replica": e["replica"],
                    "hop": e["hop"],
                    "reason": e["reason"],
                    "seq": e["seq"],
                }
            )
            continue
        if e["type"] != "span" or not e["span_id"]:
            continue
        rec = out.setdefault(
            e["span_id"],
            {
                "trace_id": e["trace_id"],
                "req_id": e.get("req_id", -1),
                "begin_seq": e["seq"],
                "stages": {},
                "request_wall": None,
                "end_seq": None,
                "cancelled": False,
                "route": [],
            },
        )
        rec["begin_seq"] = min(rec["begin_seq"], e["seq"])
        if e["phase"] == "begin":
            continue
        # ``cancelled`` closes a request envelope mid-decode (streaming
        # early convergence) exactly like ``end`` does — it carries the
        # service wall so far, so the decomposition check below covers
        # cancelled requests too (their truncated span set still sums).
        if e["name"] == "request":
            rec["request_wall"] = e["wall_s"]
            rec["end_seq"] = e["seq"]
            rec["cancelled"] = e["phase"] == "cancelled"
        elif e["name"] in STAGES:
            rec["stages"][e["name"]] = e["wall_s"]
    for span_id, hops in routes.items():
        if span_id in out:
            out[span_id]["route"] = sorted(hops, key=lambda h: h["seq"])
    for span_id, blocks in ships.items():
        if span_id in out:
            out[span_id]["shipped_blocks"] = blocks
    for rec in out.values():
        if rec["req_id"] in arrivals:
            rec["arrival_s"] = arrivals[rec["req_id"]]
    return out


def check_decomposition(requests: dict[str, dict]) -> list[str]:
    """The contract: for every request whose envelope closed with both
    device stages present, prefill + decode == request wall within
    tolerance (queued time is WAIT, deliberately outside the service
    envelope). Returns human-readable violations (empty = all good)."""
    problems: list[str] = []
    for span_id, rec in sorted(requests.items()):
        wall = rec["request_wall"]
        stages = rec["stages"]
        if wall is None or "prefill" not in stages or "decode" not in stages:
            continue  # evicted/timeout mid-flight: nothing to check
        total = stages["prefill"] + stages["decode"]
        if abs(wall - total) > max(ABS_TOL, REL_TOL * wall):
            problems.append(
                f"{span_id}: stage walls sum to {total:.6f}s but the "
                f"request reported {wall:.6f}s service"
            )
    return problems


def render_waterfall(
    requests: dict[str, dict], width: int = 32
) -> str:
    """Per-request bars, one row per stage, scaled to the slowest
    request's service wall — the 'where did this opponent's round go'
    view."""
    if not requests:
        return "(no request spans)"
    scale = max(
        (
            sum(r["stages"].values())
            for r in requests.values()
            if r["stages"]
        ),
        default=0.0,
    )
    rows: list[str] = []
    for span_id, rec in sorted(
        requests.items(), key=lambda kv: kv[1]["begin_seq"]
    ):
        wall = rec["request_wall"]
        head = f"{span_id}  (req {rec['req_id']}"
        if rec.get("arrival_s"):
            head += f", @{rec['arrival_s']:.3f}s"
        head += (
            f", service {wall:.4f}s"
            + (", CANCELLED" if rec.get("cancelled") else "")
            + ")"
            if wall is not None
            else ", open)"
        )
        hops = rec.get("route") or []
        # A disagg handoff stamps an extra reason="prefill" route at
        # the prefill replica before the ordinary decode-side route:
        # render it as its own annotation ("prefill@r0 -> decode@r2
        # (N blocks shipped)") and keep the via-chain to the replicas
        # that actually served the request.
        pre_hops = [h for h in hops if h["reason"] == "prefill"]
        hops = [h for h in hops if h["reason"] != "prefill"]
        if hops:
            # The replica path: "via r0" normally; a failover shows the
            # whole chain ("via r0 -> r1 (failover)") so a replica loss
            # is readable straight off the waterfall.
            path = " -> ".join(h["replica"] for h in hops)
            head += f"  via {path}"
            if hops[-1]["hop"] > 0:
                head += f" ({hops[-1]['reason']})"
        if pre_hops:
            dec = hops[0]["replica"] if hops else "?"
            head += (
                f"  handoff prefill@{pre_hops[0]['replica']} -> "
                f"decode@{dec}"
            )
            if rec.get("shipped_blocks"):
                head += f" ({rec['shipped_blocks']} blocks shipped)"
        rows.append(head)
        offset = 0.0
        for name in STAGES:
            if name not in rec["stages"]:
                continue
            w = rec["stages"][name]
            lead = round(offset / scale * width) if scale else 0
            fill = max(round(w / scale * width), 1) if scale else 0
            fill = min(fill, width - lead)
            bar = " " * lead + "█" * fill
            rows.append(f"  {name:<8} |{bar:<{width}}| {w:.4f}s")
            if name != "queued":  # wait time doesn't advance service
                offset += w
        rows.append("")
    return "\n".join(rows).rstrip()


def critical_path(requests: dict[str, dict]) -> str:
    """Per-trace summary: request count, total service, and the
    slowest request with its dominant stage — the first thing to read
    when an SLO capture lands."""
    traces: dict[str, list[tuple[str, dict]]] = {}
    for span_id, rec in requests.items():
        traces.setdefault(rec["trace_id"], []).append((span_id, rec))
    lines: list[str] = []
    for trace_id in sorted(traces):
        recs = traces[trace_id]
        closed = [
            (sid, r) for sid, r in recs if r["request_wall"] is not None
        ]
        lines.append(
            f"trace {trace_id or '(unstamped)'}: {len(recs)} request(s), "
            f"{len(closed)} closed"
        )
        if not closed:
            continue
        sid, worst = max(closed, key=lambda kv: kv[1]["request_wall"])
        stages = worst["stages"]
        dom = max(stages, key=stages.get) if stages else "?"
        lines.append(
            f"  critical path: {sid} at {worst['request_wall']:.4f}s "
            f"(dominant stage: {dom}"
            + (f" {stages[dom]:.4f}s)" if stages else ")")
        )
        for name in STAGES:
            total = sum(r["stages"].get(name, 0.0) for _, r in closed)
            lines.append(f"  total {name:<8} {total:.4f}s")
    return "\n".join(lines) if lines else "(no traced requests)"


def membership_changes(events: list[dict]) -> str:
    """Autoscaler transitions in seq order — fleet membership changing
    UNDER the waterfall explains a latency cliff (a request queued
    while the fleet was one replica short) without leaving the view."""
    scales = [e for e in events if e["type"] == "scale"]
    if not scales:
        return ""
    lines = ["membership changes:"]
    for s in sorted(scales, key=lambda e: e["seq"]):
        lines.append(
            f"  seq {s['seq']:>6} {s['op']:<12} "
            + " ".join(
                n
                for n in (
                    s["replica"],
                    s["direction"] and f"dir={s['direction']}",
                    s["reason"],
                    f"desired={s['desired']}",
                    f"alive={s['alive']}",
                    f"backlog={s['backlog_tokens']}",
                )
                if n
            )
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="events JSONL file to render")
    ap.add_argument(
        "--trace", help="restrict to one trace id (one debate round)"
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="machine-readable per-request records + check verdicts",
    )
    ap.add_argument(
        "--no-check",
        action="store_true",
        help="render only; skip the stage-sum consistency check",
    )
    args = ap.parse_args(argv)
    try:
        events, errors = load_events(args.path)
    except OSError as e:
        print(f"trace_view: {e}", file=sys.stderr)
        return 2
    for err in errors:
        print(f"trace_view: {err}", file=sys.stderr)
    if args.trace:
        events = [e for e in events if e.get("trace_id") == args.trace]
    requests = collect_requests(events)
    problems = [] if args.no_check else check_decomposition(requests)
    if args.json:
        print(
            json.dumps(
                {
                    "requests": requests,
                    "check_problems": problems,
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        print(render_waterfall(requests))
        print()
        print(critical_path(requests))
        scales = membership_changes(events)
        if scales:
            print()
            print(scales)
    for p in problems:
        print(f"trace_view: DECOMPOSITION VIOLATION: {p}", file=sys.stderr)
    if problems or errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
