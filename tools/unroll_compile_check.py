"""Compile-time cost of the decode-span layer-scan unroll at 70B depth.

VERDICT r3 weak item 3: ``ADVSPEC_DECODE_UNROLL=4`` quadruples the
decode-scan body for an 80-layer config; is the compile-time cost
acceptable? This measures it directly: jit-compile one decode chunk for
an 80-layer (70B-depth) config at each unroll factor in a fresh
subprocess (the knob is read at transformer import) and print one JSON
line per setting. Dims are shrunk so the 80-layer compile fits CPU RAM
— XLA codegen scales with op count (layers / unroll bodies), which is
what the knob changes, so the RATIO is the signal even though absolute
times are CPU-backend numbers.

Usage: python tools/unroll_compile_check.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD = """
import json, os, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dataclasses import replace

from adversarial_spec_tpu.models import transformer as T
from adversarial_spec_tpu.models.config import get_config
from adversarial_spec_tpu.engine.generate import decode_chunk_steps

cfg = replace(get_config("llama", "tiny"), n_layers=80)  # 70B depth
params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
B, S, max_new = 1, 128, 128
cache = T.init_cache(cfg, B, S + max_new, dtype=jnp.float32)

t0 = time.monotonic()
out = decode_chunk_steps(
    params, cfg, cache,
    jnp.zeros((B,), jnp.int32),
    jnp.zeros((B,), jnp.int32),
    jnp.zeros((B,), bool),
    jnp.zeros((B, max_new), jnp.int32),
    jnp.int32(0), jnp.int32(8),
    jnp.asarray([-1], jnp.int32),
    jax.random.key(0), jnp.float32(0.7), jnp.float32(1.0),
    prompt_len=S, chunk=8, greedy=True, top_k=0, use_top_p=False,
    use_pallas_decode=False, pallas_interpret=False, mesh=None,
)
jax.block_until_ready(out[4])
wall = time.monotonic() - t0
print(json.dumps({
    "unroll": int(os.environ.get("ADVSPEC_DECODE_UNROLL", "4")),
    "n_layers": cfg.n_layers,
    "first_call_s": round(wall, 2),
}))
"""


def main() -> int:
    results = []
    for unroll in ("1", "2", "4"):
        env = dict(os.environ)
        env.update(
            ADVSPEC_DECODE_UNROLL=unroll,
            JAX_PLATFORMS="cpu",
            # Fresh compile every time: the persistent cache would hide
            # exactly the cost being measured.
            JAX_COMPILATION_CACHE_DIR="",
        )
        t0 = time.monotonic()
        out = subprocess.run(
            [sys.executable, "-c", _CHILD],
            env=env,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if out.returncode != 0:
            print(out.stderr[-2000:], file=sys.stderr)
            return 1
        line = json.loads(out.stdout.strip().splitlines()[-1])
        line["proc_wall_s"] = round(time.monotonic() - t0, 2)
        results.append(line)
        print(json.dumps(line))
    base = results[0]["first_call_s"]
    for r in results[1:]:
        print(
            f"unroll={r['unroll']}: {r['first_call_s'] / base:.2f}x the "
            f"unroll=1 first-call (trace+compile) time at 80 layers"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
