"""Unattended TPU measurement ladder — converts a tunnel window into data.

Rounds 2-3 lesson (VERDICT r3 item 1): TPU tunnel windows in this
environment are scarce, short, and unpredictable; manual iteration wastes
them. This script runs the FULL tuning ladder the moment a probe succeeds,
with every measurement appended as one JSON line to a results file, so a
45-minute window yields the complete dataset even if the tunnel dies
mid-run.

Wedge-safety (NOTES.md round 1): a timeout-killed TPU process wedges the
tunnel for every later process. So every TPU-touching step runs in a
DETACHED child (start_new_session=True) that is NEVER signaled; the
orchestrator polls the results file and simply walks away on stall.

Resumability: each measurement has a stable "step" id; a child skips steps
already present in the results file, so re-running after a partial window
finishes only the remainder.

Ladder (phase A, one warm child process — single tunnel client, shared
compile cache):
  north_star cold+warm     bench shape: 4 opponents, 1024 prompt, 256 decode
  crossover T x {kernel,xla}  ADVSPEC_PALLAS_MIN_T decision data
                              (T in 1280/4096/8192/16384)
  long_context_16k         16k-token chunked prefill
  spec_on / spec_off       is self-speculation winning at temp 0.7?
  int8_kv / paged          quantized-KV and paged-pool deltas
  int8_weights[_kv]        weight-bandwidth lever on the fixed pipeline
  profile_trace            one traced warm run (jax.profiler)
  config2_8b_int8_greedy   BASELINE config 2 shape: 8B-class int8
                           single opponent, greedy, one chip (last —
                           short windows bank the core steps first)

Phase B (one child per env setting — knobs read at import time):
  ADVSPEC_DECODE_CHUNK in {64, 256}, ADVSPEC_DECODE_UNROLL in {1, 2},
  ADVSPEC_GAMMA in {4, 16}, ADVSPEC_BLOCK_T in {128, 256} (baselines
  chunk=128 / unroll=4 / gamma=8 / block_t=auto are phase A's
  north_star).

Phase B' (batcher γ sweep — the paged serving path):
  batcher_spec_off / batcher_gamma{4,8,16}: per-slot prompt-lookup
  speculation through the ContinuousBatcher, recording decode tok/s +
  tokens-per-verify-step + acceptance — the on-chip crossover the
  γ=8 default (engine/spec.py) is judged by.

Phase C (tiered KV — engine/kvtier.py, one child for all steps):
  tier_restart: restart rehydration through the real batcher — a
  fresh batcher re-serving a session from a COLD store vs a WARM one
  (the store the first batcher wrote through), recording the
  rehydrated prefill fraction.
  tier_pool{N}: host-tier hit ratio vs page-pool size — the pool
  shrinks below the working set, LRU eviction demotes, and the next
  round's admissions promote; the crossover_report row that judges
  how much host RAM buys at each pool size.

Phase E (fused serving kernels — ops/pallas_quant.py + the span verify
in ops/pallas_paged.py, one child for all steps):
  kernels_{int8,int4}_matmul: in-kernel dequant-matmul vs the XLA
  dequant-fusion path, warm decode tok/s both ways, byte-identical
  greedy transcripts.
  kernels_span_verify: the γ+1-position paged verify kernel vs the XLA
  gather verify through the ContinuousBatcher at γ=8.

ADVSPEC_LADDER_SMOKE=1 dry-runs the whole ladder code path on CPU with
tiny shapes (tests/test_ladder.py); smoke rows are stamped
``"smoke": true`` and excluded from resumability and from every tuning
consumer (tools/crossover_report.py, bench.py).

Usage:
  python tpu_ladder.py --out tpu_results/r04.jsonl         # orchestrate
  python tpu_ladder.py --child-main OUT                    # internal
  python tpu_ladder.py --child-env OUT STEP                # internal
  python tpu_ladder.py --child-batcher-spec OUT STEP       # internal
  python tpu_ladder.py --child-tier OUT                    # internal
  python tpu_ladder.py --child-kernels OUT                 # internal
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

BENCH_B = 4
BENCH_PROMPT = 1024
BENCH_DECODE = 256
CROSSOVER_T = (1280, 4096, 8192, 16384)
LONG_CONTEXT = 16384
# CPU smoke-mode shapes (ADVSPEC_LADDER_SMOKE=1): one source of truth
# for both children.
SMOKE_PROMPT, SMOKE_DECODE = 32, 16
SMOKE_CROSSOVER_T, SMOKE_LONG_CONTEXT = (256,), 512


# ----------------------------------------------------------------- utils


def _smoke() -> bool:
    return os.environ.get("ADVSPEC_LADDER_SMOKE") == "1"


def _done_steps(out_path: str) -> set[str]:
    """Steps already recorded. Smoke rows only count as done for smoke
    runs: a CPU smoke harvest must never satisfy (and thereby block) a
    real hardware run's resumability check, and vice versa."""
    steps: set[str] = set()
    want_smoke = _smoke()
    if os.path.exists(out_path):
        with open(out_path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                    if bool(d.get("smoke")) == want_smoke:
                        steps.add(d["step"])
                except Exception:
                    pass
    return steps


def _append(out_path: str, payload: dict) -> None:
    """Append one JSON line; line-buffered single write is atomic enough
    for the single-writer-at-a-time discipline the orchestrator enforces.
    Smoke rows are stamped so real harvest consumers (crossover_report,
    bench tuning, _done_steps) can exclude them."""
    payload = dict(payload)
    payload.setdefault("t_wall", round(time.time(), 1))
    if _smoke():
        payload["smoke"] = True
    with open(out_path, "a") as f:
        f.write(json.dumps(payload) + "\n")
        f.flush()
        os.fsync(f.fileno())


# ------------------------------------------------------------- phase A


def _child_main(out_path: str) -> int:
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine.generate import generate
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    # ADVSPEC_LADDER_SMOKE=1: run the WHOLE phase-A code path on CPU
    # with a tiny config and shrunken shapes. The ladder's measurement
    # code must never meet its first execution during a scarce tunnel
    # window — the smoke test (tests/test_ladder.py) keeps it proven.
    smoke = _smoke()
    platform = jax.devices()[0].platform
    done = _done_steps(out_path)
    _append(
        out_path,
        {
            "step": f"session_start_{int(time.time())}",
            "platform": platform,
            "n_devices": len(jax.devices()),
            "chunk": os.environ.get("ADVSPEC_DECODE_CHUNK", "128"),
            "unroll": os.environ.get("ADVSPEC_DECODE_UNROLL", "4"),
            "smoke": smoke,
        },
    )
    if platform == "cpu" and not smoke:
        # Orchestrator only launches us after a TPU probe; a CPU backend
        # here means the tunnel dropped between probe and init.
        _append(out_path, {"step": "abort_cpu_backend"})
        return 1

    global BENCH_PROMPT, BENCH_DECODE, CROSSOVER_T, LONG_CONTEXT
    if smoke:
        BENCH_PROMPT, BENCH_DECODE = SMOKE_PROMPT, SMOKE_DECODE
        CROSSOVER_T, LONG_CONTEXT = SMOKE_CROSSOVER_T, SMOKE_LONG_CONTEXT
        cfg = get_config("llama", "tiny", max_seq_len=LONG_CONTEXT + 128)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
    else:
        # One model instance serves every step: llama-1b bf16 with a
        # 16k+ window so the crossover sweep's longest context fits.
        cfg = get_config("llama", "1b", max_seq_len=LONG_CONTEXT + 512)
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    rng = __import__("random").Random(0)

    def hbm() -> dict:
        """Device memory stats (bytes), {} where the backend has none —
        the on-chip evidence for the residency-budget math
        (engine/tpu.py:hbm_budget_bytes)."""
        try:
            s = jax.devices()[0].memory_stats() or {}
            return {
                k: s[k]
                for k in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use")
                if k in s
            }
        except Exception:
            return {}

    jax.block_until_ready(params)
    if "params_resident" not in done:
        _append(out_path, {"step": "params_resident", **hbm()})
        done.add("params_resident")

    def prompts(n_tokens: int, b: int = BENCH_B) -> list[list[int]]:
        p = [rng.randrange(3, cfg.vocab_size) for _ in range(n_tokens)]
        return [list(p) for _ in range(b)]

    def run(step: str, n_prompt: int, extra: dict | None = None, **kw):
        """One measurement: warmup call (compile), then a timed call."""
        if step in done:
            return
        kw.setdefault("max_new_tokens", BENCH_DECODE)
        kw.setdefault("eos_ids", [])
        kw.setdefault("temperature", 0.7)
        kw.setdefault("seed", 0)
        p = prompts(n_prompt)
        t0 = time.monotonic()
        generate(params, cfg, p, **kw)  # warmup/compile
        t_cold = time.monotonic() - t0
        t0 = time.monotonic()
        r = generate(params, cfg, p, **kw)
        wall = time.monotonic() - t0
        _append(
            out_path,
            {
                "step": step,
                "decode_tok_s": round(r.decode_tokens / r.decode_time_s, 1),
                "decode_time_s": round(r.decode_time_s, 3),
                "prefill_time_s": round(r.prefill_time_s, 3),
                "wall_s": round(wall, 3),
                "cold_wall_s": round(t_cold, 3),
                "prompt_tokens": n_prompt,
                **(extra or {}),
            },
        )
        done.add(step)

    # 1. North star: the shape BENCH_r files record. The cold/warm split
    # tells us what the driver's bench.py (cold process, warm disk cache)
    # will see.
    run("north_star", BENCH_PROMPT)

    # 2. MIN_T crossover: kernel vs XLA decode at each context length.
    # Decides PALLAS_DECODE_MIN_T (generate.py) from data, not hope.
    for t_ctx in CROSSOVER_T:
        n_prompt = t_ctx - BENCH_DECODE
        run(f"crossover_T{t_ctx}_kernel", n_prompt, use_pallas_decode=True,
            speculative=False)
        run(f"crossover_T{t_ctx}_xla", n_prompt, use_pallas_decode=False,
            speculative=False)

    # 3. Decode levers at the bench shape.
    run("spec_off", BENCH_PROMPT, speculative=False)
    run("spec_on", BENCH_PROMPT, speculative=True)
    run("int8_kv", BENCH_PROMPT, kv_dtype="int8")
    run("paged", BENCH_PROMPT, paged=True)
    run("greedy", BENCH_PROMPT, greedy=True, temperature=0.0)

    # int8 WEIGHTS: the largest single decode lever if the step is
    # weight-bandwidth-bound (halves the bytes every step streams).
    # Round 2 measured it neutral, but that was before the lm_head_t
    # fix removed the ~3 ms relayout that dominated the step — re-judge
    # it on the fixed pipeline, alone and composed with int8 KV.
    if not {"int8_weights", "int8_weights_kv"} <= done:
        from adversarial_spec_tpu.ops.quant import quantize_params

        q_params = quantize_params(params)
        saved, params = params, q_params
        try:
            run("int8_weights", BENCH_PROMPT)
            run("int8_weights_kv", BENCH_PROMPT, kv_dtype="int8")
        finally:
            params = saved
            del q_params

    # 4. Long context: 16k chunked prefill (single chip: no sp mesh here).
    if "long_context_16k" not in done:
        p = prompts(LONG_CONTEXT, b=1)
        kw = dict(max_new_tokens=8, eos_ids=[], greedy=True,
                  speculative=False)
        generate(params, cfg, p, **kw)
        t0 = time.monotonic()
        r = generate(params, cfg, p, **kw)
        _append(
            out_path,
            {
                "step": "long_context_16k",
                "prefill_tok_s": round(LONG_CONTEXT / r.prefill_time_s, 1),
                "prefill_time_s": round(r.prefill_time_s, 3),
                "wall_s": round(time.monotonic() - t0, 3),
            },
        )
        done.add("long_context_16k")

    # 5. Profile trace: the step-gap evidence (in-loop vs device time,
    # docs/perf.md) lives in this trace.
    if "profile_trace" not in done:
        trace_dir = os.path.join(
            os.path.dirname(os.path.abspath(out_path)),
            f"trace_{int(time.time())}",
        )
        jax.profiler.start_trace(trace_dir)
        r = generate(
            params, cfg, prompts(BENCH_PROMPT),
            max_new_tokens=BENCH_DECODE, eos_ids=[], temperature=0.7,
            seed=0,
        )
        jax.profiler.stop_trace()
        _append(
            out_path,
            {
                "step": "profile_trace",
                "trace_dir": trace_dir,
                "decode_tok_s": round(r.decode_tokens / r.decode_time_s, 1),
            },
        )
        done.add("profile_trace")

    # 6. BASELINE config 2 shape, LAST in phase A (a short window should
    # spend its minutes on the core steps above first): an 8B-class
    # single opponent, greedy, one chip. bf16 8B (~16 GB weights) does
    # not fit a v5e-1's HBM beside cache+activations, so the realistic
    # single-chip serving mode is int8 weights (~8 GB). Params build
    # LEAF-WISE — init one bf16 leaf, quantize, free — so peak HBM is
    # the int8 total plus one bf16 leaf, never two full models. Random
    # weights: a perf datum needs the shapes, not the logits.
    if "config2_8b_int8_greedy" not in done:
        from adversarial_spec_tpu.ops.quant import (
            QUANTIZABLE,
            quantize_int8,
        )

        del params  # free the phase-A model's HBM before the big build
        cfg8 = get_config("llama", "tiny" if smoke else "8b")
        shapes8 = jax.eval_shape(
            lambda: T.init_params(jax.random.key(1), cfg8, dtype=jnp.bfloat16)
        )
        keyhole = [jax.random.key(7)]

        def leaf8(name: str, s):
            keyhole[0], k = jax.random.split(keyhole[0])
            w = jax.random.normal(k, s.shape, jnp.bfloat16) * 0.02
            out = quantize_int8(w) if name in QUANTIZABLE else w
            # Sync per leaf: async dispatch would otherwise keep many
            # bf16 leaves in flight and break the one-bf16-leaf peak
            # bound this builder exists for.
            return jax.block_until_ready(out)

        def build8(tree):
            return {
                name: build8(v) if isinstance(v, dict) else leaf8(name, v)
                for name, v in tree.items()
            }

        p8 = jax.block_until_ready(build8(shapes8))
        _append(out_path, {"step": "config2_8b_params", **hbm()})
        p1 = prompts(BENCH_PROMPT, b=1)
        kw8 = dict(
            max_new_tokens=BENCH_DECODE,
            eos_ids=[],
            greedy=True,
            seed=0,
            # Random weights accept ~no drafts; speculation overhead
            # would pollute the plain-decode datum (crossover steps pin
            # it off for the same reason).
            speculative=False,
        )
        generate(p8, cfg8, p1, **kw8)  # warmup/compile
        t0 = time.monotonic()
        r8 = generate(p8, cfg8, p1, **kw8)
        _append(
            out_path,
            {
                "step": "config2_8b_int8_greedy",
                "decode_tok_s": round(
                    r8.decode_tokens / r8.decode_time_s, 1
                ),
                "decode_time_s": round(r8.decode_time_s, 3),
                "prefill_time_s": round(r8.prefill_time_s, 3),
                "wall_s": round(time.monotonic() - t0, 3),
                **hbm(),
            },
        )
        done.add("config2_8b_int8_greedy")

    _append(out_path, {"step": "phase_a_complete", **hbm()})
    return 0


# ------------------------------------------------------------- phase B


ENV_STEPS = {
    "chunk64": {"ADVSPEC_DECODE_CHUNK": "64"},
    "chunk256": {"ADVSPEC_DECODE_CHUNK": "256"},
    "unroll1": {"ADVSPEC_DECODE_UNROLL": "1"},
    "unroll2": {"ADVSPEC_DECODE_UNROLL": "2"},
    "gamma4": {"ADVSPEC_GAMMA": "4"},
    "gamma16": {"ADVSPEC_GAMMA": "16"},
    "blockt128": {"ADVSPEC_BLOCK_T": "128"},
    "blockt256": {"ADVSPEC_BLOCK_T": "256"},
}


def _child_env(out_path: str, step: str) -> int:
    """Bench-shape warm measurement under one env-knob setting (the knob
    was exported by the orchestrator before spawning us)."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine.generate import generate
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    smoke = _smoke()
    if jax.devices()[0].platform == "cpu" and not smoke:
        _append(out_path, {"step": f"{step}_abort_cpu"})
        return 1
    if smoke:
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        n_prompt, n_decode = SMOKE_PROMPT, SMOKE_DECODE
    else:
        cfg = get_config("llama", "1b")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
        n_prompt, n_decode = BENCH_PROMPT, BENCH_DECODE
    rng = __import__("random").Random(0)
    p = [rng.randrange(3, cfg.vocab_size) for _ in range(n_prompt)]
    prompts = [list(p) for _ in range(BENCH_B)]
    kw = dict(max_new_tokens=n_decode, eos_ids=[], temperature=0.7,
              seed=0)
    generate(params, cfg, prompts, **kw)
    t0 = time.monotonic()
    r = generate(params, cfg, prompts, **kw)
    _append(
        out_path,
        {
            "step": step,
            "decode_tok_s": round(r.decode_tokens / r.decode_time_s, 1),
            "decode_time_s": round(r.decode_time_s, 3),
            "wall_s": round(time.monotonic() - t0, 3),
            "env": {k: os.environ[k] for k in ENV_STEPS[step]},
        },
    )
    return 0


# Phase B': the γ sweep through the ContinuousBatcher — per-slot
# prompt-lookup speculation on the PAGED serving path (the path the CLI
# actually drives; phase B's gamma4/gamma16 sweep the dense generate()
# loop). γ is a width-vs-waste trade: too small caps the accepted span,
# too large pays a wider verify forward for drafts the sampler rejects —
# the on-chip crossover against batcher_spec_off is the data the γ=8
# default (engine/spec.py) is judged by. Knobs travel as env because
# each child is a fresh process: spec.py reads ADVSPEC_GAMMA /
# ADVSPEC_SPECULATIVE at import and the batcher snapshots that config
# at construction.
BATCHER_SPEC_STEPS = {
    "batcher_spec_off": {"ADVSPEC_SPECULATIVE": "0"},
    "batcher_gamma4": {"ADVSPEC_GAMMA": "4"},
    "batcher_gamma8": {"ADVSPEC_GAMMA": "8"},
    "batcher_gamma16": {"ADVSPEC_GAMMA": "16"},
}


def _child_batcher_spec(out_path: str, step: str) -> int:
    """One warm drain then one timed drain of the bench-shaped opponent
    pool through the ContinuousBatcher under this step's speculation
    knobs, recording decode tok/s, mean tokens per verify step, and the
    acceptance rate."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    smoke = _smoke()
    if jax.devices()[0].platform == "cpu" and not smoke:
        _append(out_path, {"step": f"{step}_abort_cpu"})
        return 1
    if smoke:
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        n_prompt, n_decode = SMOKE_PROMPT, SMOKE_DECODE
    else:
        cfg = get_config("llama", "1b")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
        n_prompt, n_decode = BENCH_PROMPT, BENCH_DECODE
    rng = __import__("random").Random(0)
    base = [rng.randrange(3, cfg.vocab_size) for _ in range(n_prompt)]

    def drain():
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=BENCH_B,
            max_new_cap=n_decode,
            page_size=64,
            capacity_tokens=1 << 16,
            greedy=True,
            prefix_cache=False,
        )
        for i in range(BENCH_B):
            b.submit(
                SchedRequest(
                    req_id=i,
                    prompt_ids=list(base),
                    max_new_tokens=n_decode,
                )
            )
        spec_mod.reset_stats()
        t0 = time.monotonic()
        results = b.run_all()
        wall = time.monotonic() - t0
        toks = sum(r.n_generated for r in results)
        return toks, wall, b.decode_time_s, spec_mod.stats.snapshot()

    drain()  # warm: compiles every program this shape dispatches
    toks, wall, decode_s, snap = drain()
    _append(
        out_path,
        {
            "step": step,
            "decode_tok_s": round(toks / max(decode_s, 1e-9), 1),
            "decode_time_s": round(decode_s, 3),
            "tokens_per_step": snap["tokens_per_step"],
            "acceptance_rate": snap["acceptance_rate"],
            "spec_steps": snap["spec_steps"],
            "rolled_back_pages": snap["rolled_back_pages"],
            "wall_s": round(wall, 3),
            "env": {k: os.environ[k] for k in BATCHER_SPEC_STEPS[step]},
        },
    )
    return 0


# Phase C (tiered KV): page-pool sizes for the host-tier hit-ratio
# sweep. The bench pool (4 opponents x (1024 prompt + 256 decode)) needs
# ~5120 resident tokens; the smaller entries force LRU pressure, so the
# sweep maps "how much re-prefill does host RAM absorb" against pool
# size. Step names are stable across smoke/real runs (smoke scales the
# shapes, and smoke rows are excluded from consumers anyway).
TIER_POOL_TOKENS = (4096, 8192, 16384)
TIER_STEPS = ("tier_restart",) + tuple(
    f"tier_pool{p}" for p in TIER_POOL_TOKENS
)

# Phase D (weight residency, engine/weightres.py): opponent-pool size
# vs HBM budget — (pool models, budget models). (2,2) is the no-swap
# control; (4,2) the paper's 4-opponent pool under half residency (the
# BENCH_residency acceptance point); (4,3) the one-spare-slot shape
# where the prefetch thread can overlap every promotion.
RES_SWEEP = ((2, 2), (4, 2), (4, 3))
RES_STEPS = tuple(f"res_pool{p}b{b}" for p, b in RES_SWEEP)


def _child_tier(out_path: str) -> int:
    """Phase C: tiered-KV measurements through the real batcher, one
    warm child for every step (shared model + compile cache)."""
    import shutil
    import tempfile

    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import kvtier as kvtier_mod
    from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config

    smoke = _smoke()
    if jax.devices()[0].platform == "cpu" and not smoke:
        _append(out_path, {"step": "tier_abort_cpu"})
        return 1
    if smoke:
        cfg = get_config("llama", "tiny")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.float32)
        n_prompt, n_decode, scale = SMOKE_PROMPT * 8, SMOKE_DECODE, 16
    else:
        cfg = get_config("llama", "1b")
        params = T.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
        n_prompt, n_decode, scale = BENCH_PROMPT, BENCH_DECODE, 1
    done = _done_steps(out_path)
    spec_mod.configure(enabled=False)  # isolate the tier effect
    rng = __import__("random").Random(0)
    seg = [rng.randrange(3, cfg.vocab_size) for _ in range(16)]
    base = (seg * (n_prompt // len(seg) + 1))[:n_prompt]

    def rounds(tier_on, capacity, store_dir, n_rounds=2):
        kvtier_mod.configure(
            enabled=tier_on, host_mb=256, store_dir=store_dir
        )
        prefix_mod.configure(enabled=True, max_pages=0)
        prefix_mod.reset_stats()
        kvtier_mod.reset_stats()
        b = ContinuousBatcher(
            params,
            cfg,
            max_batch=BENCH_B,
            max_new_cap=n_decode,
            page_size=64,
            capacity_tokens=capacity,
            greedy=True,
        )
        doc = list(base)
        per_round, toks = [], 0
        t0 = time.monotonic()
        for _ in range(n_rounds):
            before = prefix_mod.stats.prefilled_tokens
            for i in range(BENCH_B):
                b.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=list(doc),
                        max_new_tokens=n_decode,
                    )
                )
            results = b.run_all()
            toks += sum(r.n_generated for r in results)
            per_round.append(prefix_mod.stats.prefilled_tokens - before)
            doc = doc + [
                rng.randrange(3, cfg.vocab_size)
                for _ in range(max(n_decode, 16))
            ]
        wall = time.monotonic() - t0
        return (
            per_round,
            toks,
            wall,
            b.decode_time_s,
            kvtier_mod.stats.snapshot(),
        )

    roomy = 1 << (17 if not smoke else 14)  # no pressure: restart story
    if "tier_restart" not in done:
        # Throwaway warmup drain FIRST: the cold run would otherwise be
        # the process's first batcher drive and its wall would measure
        # jit compilation, not the store's rehydration cost.
        rounds(True, roomy, "")
        store = tempfile.mkdtemp(prefix="ladder_tier_store_")
        try:
            cold_rounds, _, cold_wall, _, _ = rounds(True, roomy, store)
            warm_rounds, _, warm_wall, _, snap = rounds(True, roomy, store)
            off_rounds, _, _, _, _ = rounds(False, roomy, "")
            _append(
                out_path,
                {
                    "step": "tier_restart",
                    "prefill_tokens_cold": cold_rounds,
                    "prefill_tokens_warm": warm_rounds,
                    "prefill_tokens_tier_off": off_rounds,
                    "rehydrated_fraction": round(
                        1.0 - sum(warm_rounds) / max(sum(off_rounds), 1), 4
                    ),
                    "rehydrated_tokens": snap["rehydrated_tokens"],
                    "wall_cold_s": round(cold_wall, 3),
                    "wall_warm_s": round(warm_wall, 3),
                },
            )
        finally:
            shutil.rmtree(store, ignore_errors=True)

    for p in TIER_POOL_TOKENS:
        step = f"tier_pool{p}"
        if step in done:
            continue
        # Floor: one grown-round request (bucketed prompt + budget) must
        # still fit; with BENCH_B opponents the sweep stays under the
        # working set, so LRU pressure fires at every sweep point.
        capacity = max(p // scale, 1024)
        per_round, toks, wall, decode_s, snap = rounds(True, capacity, "")
        _append(
            out_path,
            {
                "step": step,
                "pool_tokens": capacity,
                "decode_tok_s": round(toks / max(decode_s, 1e-9), 1),
                "prefill_tokens_per_round": per_round,
                "host_hit_ratio": snap["host_hit_rate"],
                "promoted_tokens": snap["promoted_tokens"],
                "demoted_tokens": snap["demoted_tokens"],
                "wall_s": round(wall, 3),
            },
        )
    return 0


def _child_residency(out_path: str) -> int:
    """Phase D: weight-residency sweep (pool size vs HBM budget) — one
    warm child, a fresh TpuEngine per sweep point (residency is the
    engine-lifetime state under test). Smoke mode drives the four tiny
    families on CPU; hardware runs register four synthetic 1b pool
    members so the swapped bytes are production-shaped."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax

    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine import weightres
    from adversarial_spec_tpu.engine.tpu import TpuEngine
    from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

    smoke = _smoke()
    if jax.devices()[0].platform == "cpu" and not smoke:
        _append(out_path, {"step": "res_abort_cpu"})
        return 1
    if smoke:
        pool = [
            "random-tiny",
            "random-gemma-tiny",
            "random-mistral-tiny",
            "random-qwen-tiny",
        ]
        n_decode = SMOKE_DECODE
    else:
        from adversarial_spec_tpu.engine.registry import (
            ModelSpec,
            save_registry_entry,
        )

        pool = [f"res-1b-{i}" for i in range(4)]
        for alias in pool:
            save_registry_entry(
                ModelSpec(alias=alias, family="llama", size="1b")
            )
        n_decode = 32
    done = _done_steps(out_path)
    spec_mod.configure(enabled=False)  # isolate the residency effect
    sampling = SamplingParams(max_new_tokens=n_decode, greedy=True, seed=0)

    def arm(aliases, budget: int | None, paging: bool, n_rounds=4):
        if budget is None:
            os.environ.pop("ADVSPEC_HBM_BUDGET_BYTES", None)
        else:
            os.environ["ADVSPEC_HBM_BUDGET_BYTES"] = str(budget)
        weightres.configure(enabled=paging, host_mb=8192)
        weightres.reset_stats()
        eng = TpuEngine()
        t0 = time.monotonic()
        for rnd in range(1, n_rounds + 1):
            reqs = [
                ChatRequest(
                    model=f"tpu://{a}",
                    system="You are an adversarial spec critic.",
                    user=f"Critique the document.\nDebate round {rnd}",
                )
                for a in aliases
            ]
            outs = eng.chat(reqs, sampling)
            if not all(c.ok for c in outs):
                raise RuntimeError(
                    f"residency arm failed: {[c.error for c in outs]}"
                )
            eng.check_residency_invariants()
        sizes = {
            a: e.bytes_device or e.bytes_host
            for a, e in eng.ledger._entries.items()
        }
        return time.monotonic() - t0, weightres.snapshot(), sizes

    # Unconstrained probe once: per-model bytes for the budget math.
    _, _, sizes = arm(pool, None, True, n_rounds=1)
    by_size = sorted(sizes.values(), reverse=True)
    try:
        for p, b in RES_SWEEP:
            step = f"res_pool{p}b{b}"
            if step in done:
                continue
            budget = int(sum(by_size[:b]) * 1.05)
            wall_on, snap_on, _ = arm(pool[:p], budget, True)
            wall_off, snap_off, _ = arm(pool[:p], budget, False)
            _append(
                out_path,
                {
                    "step": step,
                    "pool_models": p,
                    "budget_models": b,
                    "budget_bytes": budget,
                    "load_wall_resident_s": round(
                        snap_on["weight_load_wall_s"], 4
                    ),
                    "load_wall_thrash_s": round(
                        snap_off["weight_load_wall_s"], 4
                    ),
                    "load_wall_ratio": round(
                        snap_off["weight_load_wall_s"]
                        / max(snap_on["weight_load_wall_s"], 1e-9),
                        3,
                    ),
                    "swap_overlap_fraction": snap_on[
                        "swap_overlap_fraction"
                    ],
                    "promotions": snap_on["promotions"],
                    "demotions": snap_on["demotions"],
                    "thrash_loads": snap_off["loads"],
                    "wall_on_s": round(wall_on, 3),
                    "wall_off_s": round(wall_off, 3),
                },
            )
    finally:
        os.environ.pop("ADVSPEC_HBM_BUDGET_BYTES", None)
    return 0


# ------------------------------------------------------------- phase E

KERNEL_STEPS = (
    "kernels_int8_matmul",
    "kernels_int4_matmul",
    "kernels_span_verify",
)


def _child_kernels(out_path: str) -> int:
    """Phase E: fused serving kernels (ops/pallas_quant.py dequant-
    matmuls + the multi-position span verify in ops/pallas_paged.py)
    vs their XLA paths on real hardware — decode tok/s both ways with
    byte-identical greedy transcripts (a speedup with different tokens
    is a bug, not a win). Each arm runs twice; the second (warm) run is
    the measurement. Smoke mode drives the same code on CPU with the
    kernels in interpret mode."""
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    import jax
    import jax.numpy as jnp

    from adversarial_spec_tpu.engine import spec as spec_mod
    from adversarial_spec_tpu.engine.generate import generate
    from adversarial_spec_tpu.engine.scheduler import (
        ContinuousBatcher,
        SchedRequest,
    )
    from adversarial_spec_tpu.models import transformer as T
    from adversarial_spec_tpu.models.config import get_config
    from adversarial_spec_tpu.ops import quant

    smoke = _smoke()
    platform = jax.devices()[0].platform
    if platform == "cpu" and not smoke:
        _append(out_path, {"step": "kernels_abort_cpu"})
        return 1
    size = "tiny" if smoke else "1b"
    cfg = get_config("llama", size)
    base = T.init_params(
        jax.random.key(0), cfg,
        dtype=jnp.float32 if smoke else jnp.bfloat16,
    )
    # Smoke halves the decode budget: interpret-mode kernels pay real
    # wall per token, and 8 tokens already cross several verify spans.
    n_prompt, n_decode = (
        (SMOKE_PROMPT, SMOKE_DECODE // 2)
        if smoke
        else (BENCH_PROMPT, BENCH_DECODE)
    )
    prompts = [
        [3 + ((i * 7 + r) % (cfg.vocab_size - 3)) for i in range(n_prompt)]
        for r in range(2)
    ]
    done = _done_steps(out_path)

    def mm_arm(params, fused: bool):
        t0 = time.monotonic()
        res = generate(
            params, cfg, prompts,
            max_new_tokens=n_decode, eos_ids=[], greedy=True,
            speculative=False, share_prefix=False,
            use_pallas_matmul=fused,
        )
        wall = time.monotonic() - t0
        toks = int(res.n_generated.sum())
        return res.tokens.tolist(), toks / max(wall, 1e-9)

    for fmt in ("int8", "int4"):
        step = f"kernels_{fmt}_matmul"
        if step in done:
            continue
        qp = quant.quantize_params(base, fmt=fmt)
        if not smoke:  # warm both programs: measure steady state, not
            mm_arm(qp, True)  # the cold compile (pointless under
            mm_arm(qp, False)  # interpret mode, where time is fake)
        t_on, tps_on = mm_arm(qp, True)
        t_off, tps_off = mm_arm(qp, False)
        del qp
        _append(
            out_path,
            {
                "step": step,
                "platform": platform,
                "model": f"llama-{size}",
                "decode_tok_s_fused": round(tps_on, 1),
                "decode_tok_s_xla": round(tps_off, 1),
                "speedup": round(tps_on / max(tps_off, 1e-9), 3),
                "tokens_identical": t_on == t_off,
            },
        )

    step = "kernels_span_verify"
    if step not in done:
        qp = quant.quantize_params(base, fmt="int4")
        gamma = 8
        prompt = [5 + (i % 7) for i in range(n_prompt)]

        def verify_arm(use_pallas: bool):
            spec_mod.configure(enabled=True, gamma=gamma)
            spec_mod.reset_stats()
            b = ContinuousBatcher(
                qp, cfg, max_batch=2, max_new_cap=n_decode,
                page_size=64, greedy=True, prefix_cache=False,
                speculative=True, gamma=gamma,
                use_pallas_matmul=False,  # isolate the verify kernel
            )
            b._use_pallas = use_pallas
            if smoke:
                b._pallas_interpret = True
            t0 = time.monotonic()
            for i in range(2):
                b.submit(
                    SchedRequest(
                        req_id=i, prompt_ids=list(prompt),
                        max_new_tokens=n_decode,
                    )
                )
            results = b.run_all()
            wall = time.monotonic() - t0
            toks = {r.req_id: r.tokens.tolist() for r in results}
            n = sum(len(t) for t in toks.values())
            return toks, n / max(wall, 1e-9), spec_mod.stats.snapshot()

        if not smoke:  # warm (skipped under interpret — time is fake)
            verify_arm(True)
            verify_arm(False)
        t_on, tps_on, snap = verify_arm(True)
        t_off, tps_off, _ = verify_arm(False)
        _append(
            out_path,
            {
                "step": step,
                "platform": platform,
                "model": f"llama-{size}",
                "gamma": gamma,
                "decode_tok_s_kernel": round(tps_on, 1),
                "decode_tok_s_xla": round(tps_off, 1),
                "speedup": round(tps_on / max(tps_off, 1e-9), 3),
                "tokens_identical": t_on == t_off,
                "acceptance_rate": snap["acceptance_rate"],
                "tokens_per_step": snap["tokens_per_step"],
            },
        )
    return 0


def _clean_env(knobs: dict[str, str] | None = None) -> dict[str, str]:
    """Child env for a measurement: ambient ADVSPEC_* tuning knobs are
    stripped so the harvest records CANONICAL defaults (an operator's
    exported kill-switch or chunk override would otherwise contaminate
    every step, and a recommendation derived from contaminated data
    flaps on the next cycle). The swept knobs come back via ``knobs``;
    ADVSPEC_LADDER_SMOKE survives because it is a mode, not a tuning
    knob."""
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("ADVSPEC_") or k == "ADVSPEC_LADDER_SMOKE"
    }
    env.update(knobs or {})
    return env


# --------------------------------------------------------- orchestrator


def _wait_progress(out_path: str, child: subprocess.Popen,
                   stall_s: float) -> bool:
    """Poll the results file until the child exits or makes no progress
    for stall_s. Returns True iff the child exited on its own. On stall
    the child is LEFT RUNNING (wedge-safety) and we walk away."""
    last_size = -1
    last_change = time.monotonic()
    while True:
        size = os.path.getsize(out_path) if os.path.exists(out_path) else 0
        if size != last_size:
            last_size = size
            last_change = time.monotonic()
        if child.poll() is not None:
            return True
        if time.monotonic() - last_change > stall_s:
            return False
        time.sleep(5.0)


def orchestrate(out_path: str) -> int:
    sys.path.insert(0, REPO)
    from bench import _probe_tpu

    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)

    if not _probe_tpu():
        print("ladder: probe failed (no TPU); nothing run", file=sys.stderr)
        return 3

    done = _done_steps(out_path)
    if "phase_a_complete" not in done:
        print("ladder: TPU probe ok — phase A", file=sys.stderr)
        env = _clean_env()
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child-main",
             out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True, env=env, cwd=REPO,
        )
        # First step includes jax init + first 1b compile: be generous,
        # but a 20-minute silence means the tunnel hung — walk away
        # (never kill).
        if not _wait_progress(out_path, child, stall_s=1200.0):
            print("ladder: phase A stalled; abandoning child",
                  file=sys.stderr)
            return 2
        done = _done_steps(out_path)
        if "phase_a_complete" not in done:
            print("ladder: phase A child exited incomplete",
                  file=sys.stderr)
            return 2

    phase_b = [("--child-env", s, k) for s, k in ENV_STEPS.items()] + [
        ("--child-batcher-spec", s, k)
        for s, k in BATCHER_SPEC_STEPS.items()
    ]
    for flag, step, knobs in phase_b:
        if step in done:
            continue
        if not _probe_tpu(timeout_s=60.0):
            print(f"ladder: tunnel gone before {step}", file=sys.stderr)
            return 2
        env = _clean_env(knobs)
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), flag,
             out_path, step],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True, env=env, cwd=REPO,
        )
        if not _wait_progress(out_path, child, stall_s=900.0):
            print(f"ladder: {step} stalled; abandoning", file=sys.stderr)
            return 2

    # Phase C (tiered KV): one warm child records every remaining tier
    # step (restart rehydration + the pool-size sweep share one model).
    if any(s not in _done_steps(out_path) for s in TIER_STEPS):
        if not _probe_tpu(timeout_s=60.0):
            print("ladder: tunnel gone before tier phase", file=sys.stderr)
            return 2
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child-tier",
             out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True, env=_clean_env(), cwd=REPO,
        )
        if not _wait_progress(out_path, child, stall_s=900.0):
            print("ladder: tier phase stalled; abandoning", file=sys.stderr)
            return 2

    # Phase D (weight residency): pool-size vs HBM-budget sweep, one
    # warm child (fresh engines inside — residency is per-engine).
    if any(s not in _done_steps(out_path) for s in RES_STEPS):
        if not _probe_tpu(timeout_s=60.0):
            print(
                "ladder: tunnel gone before residency phase",
                file=sys.stderr,
            )
            return 2
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--child-residency", out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True, env=_clean_env(), cwd=REPO,
        )
        if not _wait_progress(out_path, child, stall_s=900.0):
            print(
                "ladder: residency phase stalled; abandoning",
                file=sys.stderr,
            )
            return 2

    # Phase E (fused kernels): fused-vs-XLA A/B of the dequant-matmul
    # and span-verify kernels, one warm child.
    if any(s not in _done_steps(out_path) for s in KERNEL_STEPS):
        if not _probe_tpu(timeout_s=60.0):
            print(
                "ladder: tunnel gone before kernels phase",
                file=sys.stderr,
            )
            return 2
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--child-kernels", out_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True, env=_clean_env(), cwd=REPO,
        )
        if not _wait_progress(out_path, child, stall_s=900.0):
            print(
                "ladder: kernels phase stalled; abandoning",
                file=sys.stderr,
            )
            return 2

    done = _done_steps(out_path)
    missing = [
        s
        for s in list(ENV_STEPS)
        + list(BATCHER_SPEC_STEPS)
        + list(TIER_STEPS)
        + list(RES_STEPS)
        + list(KERNEL_STEPS)
        if s not in done
    ]
    if missing:
        # A phase-B child exited without recording its step (crash or
        # cpu-backend abort): not complete — the session loop retries.
        print(f"ladder: phase B incomplete: {missing}", file=sys.stderr)
        return 2
    _append(out_path, {"step": "ladder_complete"})
    print("ladder: complete", file=sys.stderr)
    return 0


def main() -> int:
    args = sys.argv[1:]
    if "--child-main" in args:
        return _child_main(args[args.index("--child-main") + 1])
    if "--child-env" in args:
        i = args.index("--child-env")
        return _child_env(args[i + 1], args[i + 2])
    if "--child-batcher-spec" in args:
        i = args.index("--child-batcher-spec")
        return _child_batcher_spec(args[i + 1], args[i + 2])
    if "--child-tier" in args:
        return _child_tier(args[args.index("--child-tier") + 1])
    if "--child-residency" in args:
        return _child_residency(args[args.index("--child-residency") + 1])
    if "--child-kernels" in args:
        return _child_kernels(args[args.index("--child-kernels") + 1])
    out = "tpu_results/ladder.jsonl"
    if "--out" in args:
        out = args[args.index("--out") + 1]
    return orchestrate(out)


if __name__ == "__main__":
    sys.exit(main())
