#!/usr/bin/env bash
# Background TPU-window harvester. Retries the measurement ladder every
# 5 minutes until it completes once, then exits. Wedge-safe by
# construction: tpu_ladder.py never signals a TPU-holding process.
#
#   nohup bash tpu_session.sh >> tpu_results/session.log 2>&1 &
#
# Results accumulate (resumably) in $OUT; "ladder_complete" marks done.
set -u
cd "$(dirname "$0")"
OUT="${1:-tpu_results/r04.jsonl}"
mkdir -p "$(dirname "$OUT")"

# Preflight: static gates before burning a TPU window, fastest first.
# Stage 1 lints only the files changed vs main (seconds even as the
# rule set grows) so a broken edit aborts before the full pass; stage 2
# is the full gate — graftlint over the whole repo + mutmut-config
# sanity, with --full adding the unroll compile check (minutes of CPU —
# fine while waiting for a window). A failure aborts the session: a
# repo that doesn't lint clean should not spend accelerator time.
echo "$(date -u +%FT%TZ) session: preflight-fast (tools/lint_all.py --changed)"
if ! JAX_PLATFORMS=cpu python tools/lint_all.py --changed; then
  echo "$(date -u +%FT%TZ) session: fast preflight FAILED — aborting"
  exit 1
fi
echo "$(date -u +%FT%TZ) session: preflight (tools/lint_all.py --full)"
if ! JAX_PLATFORMS=cpu python tools/lint_all.py --full; then
  echo "$(date -u +%FT%TZ) session: preflight FAILED — aborting"
  exit 1
fi

finish() {
  # Post-harvest actions: decision report + a tuned bench record, so a
  # window that opens while nobody is watching still leaves the full
  # story (tpu_results/report.txt + bench_tuned.json) on disk.
  echo "$(date -u +%FT%TZ) session: writing report + tuned bench"
  python tools/crossover_report.py "$OUT" > tpu_results/report.txt 2>&1
  python bench.py > tpu_results/bench_tuned.json 2>> tpu_results/report.txt
  echo "$(date -u +%FT%TZ) session: done"
  exit 0
}

while true; do
  if grep -q '"step": "ladder_complete"' "$OUT" 2>/dev/null; then
    echo "$(date -u +%FT%TZ) session: ladder complete"
    finish
  fi
  echo "$(date -u +%FT%TZ) session: attempting ladder"
  python tpu_ladder.py --out "$OUT"
  rc=$?
  echo "$(date -u +%FT%TZ) session: ladder rc=$rc"
  if [ "$rc" = "0" ]; then
    finish
  fi
  sleep 300
done
