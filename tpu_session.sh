#!/usr/bin/env bash
# Background TPU-window harvester. Retries the measurement ladder every
# 5 minutes until it completes once, then exits. Wedge-safe by
# construction: tpu_ladder.py never signals a TPU-holding process.
#
#   nohup bash tpu_session.sh >> tpu_results/session.log 2>&1 &
#
# Results accumulate (resumably) in $OUT; "ladder_complete" marks done.
set -u
cd "$(dirname "$0")"
OUT="${1:-tpu_results/r04.jsonl}"
mkdir -p "$(dirname "$OUT")"

while true; do
  if grep -q '"step": "ladder_complete"' "$OUT" 2>/dev/null; then
    echo "$(date -u +%FT%TZ) session: ladder complete — exiting"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) session: attempting ladder"
  python tpu_ladder.py --out "$OUT"
  rc=$?
  echo "$(date -u +%FT%TZ) session: ladder rc=$rc"
  if [ "$rc" = "0" ]; then
    exit 0
  fi
  sleep 300
done
